"""Learner→engine weight refresh over the int8 blockwise wire.

The wire format is :mod:`ray_tpu.parallel.quantization`'s (values int8
``[nblocks, block_size]`` + f32 per-block scales — the EQuARX
collective format reused as a transport codec): each float leaf of the
param tree ships ~4x smaller than f32, which is what makes per-round
in-flight refresh affordable when the learner and engines are on
different slices (sebulba). Non-float leaves (and anything a caller
marks raw) ship verbatim.

The refresh is **version-stamped at the source**: ``pack_weights``
bakes the monotone policy version into the payload, the engine's
double-buffered swap applies it between decode steps, and every token
the engine emits afterwards carries that version — so a trajectory's
per-token version column is an exact record of which policy generated
each token (the staleness ledger PPO importance ratios are audited
against).

Dequantization runs on the *caller's* thread (the actor call that
delivers the payload), never on the engine step thread: the step
thread's only cost is a pointer swap.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.parallel.quantization import (DEFAULT_BLOCK_SIZE,
                                           dequantize_int8_np,
                                           quantize_int8_np)

_SEP = "/"


def _flatten(tree: Dict[str, Any], prefix: str = ""
             ) -> List[Tuple[str, Any]]:
    out: List[Tuple[str, Any]] = []
    for k in sorted(tree):
        v = tree[k]
        key = f"{prefix}{_SEP}{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.extend(_flatten(v, key))
        else:
            out.append((key, v))
    return out


def _unflatten(entries: Dict[str, Any]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for key, v in entries.items():
        parts = key.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return root


def pack_weights(params: Dict[str, Any], version: int,
                 block_size: int = DEFAULT_BLOCK_SIZE
                 ) -> Dict[str, Any]:
    """Quantize a (nested-dict) param tree to the int8 wire payload.
    Float leaves become ``{"q", "scales", "shape", "dtype"}``; integer
    and boolean leaves ship raw. The payload is pure numpy — it crosses
    the object store with the zero-copy serializer."""
    entries: Dict[str, Dict[str, Any]] = {}
    for key, leaf in _flatten(params):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f":
            q, scales = quantize_int8_np(arr, block_size)
            entries[key] = {"q": q, "scales": scales,
                            "shape": arr.shape, "dtype": str(arr.dtype)}
        else:
            entries[key] = {"raw": arr}
    return {"version": int(version), "block_size": int(block_size),
            "entries": entries}


def unpack_weights(packed: Dict[str, Any]
                   ) -> Tuple[Dict[str, Any], int]:
    """Invert :func:`pack_weights` → ``(params, version)``."""
    out: Dict[str, Any] = {}
    for key, e in packed["entries"].items():
        if "raw" in e:
            out[key] = e["raw"]
        else:
            out[key] = dequantize_int8_np(
                e["q"], e["scales"], shape=e["shape"],
                dtype=np.dtype(e["dtype"]))
    return _unflatten(out), int(packed["version"])


def packed_wire_bytes(packed: Dict[str, Any]) -> int:
    """Actual payload bytes of one refresh (int8 values + f32 scales +
    raw leaves) — the number the bench's compression column reports."""
    total = 0
    for e in packed["entries"].values():
        if "raw" in e:
            total += e["raw"].nbytes
        else:
            total += e["q"].nbytes + e["scales"].nbytes
    return total


def _f32_bytes(packed: Dict[str, Any]) -> int:
    total = 0
    for e in packed["entries"].values():
        if "raw" in e:
            total += e["raw"].nbytes
        else:
            total += 4 * int(np.prod(e["shape"])) if e["shape"] else 4
    return total


class WeightPublisher:
    """Monotone-versioned weight fan-out to a set of engines.

    Targets may be in-process :class:`~ray_tpu.serve.llm_engine.
    LLMEngine` objects (``stage_weights`` — dequantized HERE, on the
    publisher's thread) or remote handles exposing ``sync_weights``
    (the packed payload ships; the replica dequantizes on its own actor
    thread). Either way the engine step thread only ever pointer-swaps.
    """

    def __init__(self, engines: List[Any],
                 block_size: int = DEFAULT_BLOCK_SIZE,
                 recorder=None):
        self._engines = list(engines)
        self._block_size = block_size
        self._recorder = recorder
        self._lock = threading.Lock()
        self._version = 0
        self._publishes = 0
        self._wire_bytes = 0
        self._f32_bytes = 0
        self._publish_wall_s = 0.0

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def publish(self, params: Dict[str, Any]) -> int:
        """Pack + fan out one refresh; returns the new version."""
        t0 = time.monotonic()
        with self._lock:
            self._version += 1
            version = self._version
        packed = pack_weights(params, version, self._block_size)
        unpacked = None
        for eng in self._engines:
            if hasattr(eng, "stage_weights"):
                if unpacked is None:
                    unpacked, _ = unpack_weights(packed)
                eng.stage_weights(unpacked, version)
            else:
                eng.sync_weights(packed)
        wall = time.monotonic() - t0
        with self._lock:
            self._publishes += 1
            self._wire_bytes += packed_wire_bytes(packed)
            self._f32_bytes += _f32_bytes(packed)
            self._publish_wall_s += wall
        return version

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "version": self._version,
                "publishes": self._publishes,
                "wire_bytes_total": self._wire_bytes,
                "f32_bytes_total": self._f32_bytes,
                "compression": (round(self._f32_bytes
                                      / self._wire_bytes, 3)
                                if self._wire_bytes else None),
                "publish_wall_s": round(self._publish_wall_s, 4),
            }
