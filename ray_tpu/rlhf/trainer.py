"""The closed PPO-RLHF loop: rollout rounds → sharded multi-learner
streaming updates → in-flight weight republish, under a staleness bound.

Round anatomy (one ``train_round`` call):

1. Deterministic per-round prompt suffixes are appended to the shared
   system prompt and admitted to the rollout engines under the
   ``max_weight_lag`` gate.
2. Trajectory blocks stream back in completion order;
   ``LearnerGroup.update_from_stream_sharded`` re-chunks them
   deterministically across ALL learners and closes synchronous
   gradient rounds as shards fill.
3. After every ``sync_every_updates`` applied rounds the ``on_round``
   hook packs the fresh learner weights over the int8 wire and stages
   them on every engine — **while those engines are still decoding the
   round's remaining trajectories**. The engine step thread pointer-
   swaps between decode steps; tokens emitted after the swap carry the
   new policy version, so one trajectory's ``versions`` row can
   legitimately read ``[3 3 3 4 4 …]`` — that is the in-flight refresh
   observable the chaos tests pin down.

The staleness gate cannot deadlock: ``publish`` stages synchronously,
and ``LLMEngine.weight_version`` reports a *staged* version
immediately, so the learner-side version and the engine-side version
never diverge by more than the one publish that is mid-stage.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rlhf.config import RLHFConfig
from ray_tpu.rlhf.rollout import RolloutEngine
from ray_tpu.rlhf.weight_sync import WeightPublisher


class PolicyLearner:
    """Token-level PPO learner over the serving stack's transformer.

    Implements the :class:`ray_tpu.rllib.learner.Learner` protocol
    (``compute_gradients`` / ``apply_gradients`` / ``get_weights`` /
    ``set_weights`` / ``update_from_batch``) so ``LearnerGroup`` can
    run it locally or as remote data-parallel replicas unchanged.

    The loss is exact PPO, not an approximation: the rollout batch's
    ``logprobs`` column was captured by the engine from the *behavior*
    policy's own forward pass (the quantized weights that actually
    generated each token), so ``exp(new_lp - logprobs)`` is the true
    importance ratio, and the ``versions`` column tells you which
    policy that was.
    """

    def __init__(self, model: Dict[str, Any],
                 learning_rate: float = 1e-3,
                 clip_eps: float = 0.2, grad_clip: float = 1.0,
                 seed: int = 0):
        import jax
        import optax
        from ray_tpu.models import TransformerConfig, init_params
        from ray_tpu.serve.llm_engine import _resolve_dtype
        model = dict(model)
        model["dtype"] = _resolve_dtype(model.get("dtype", "float32"))
        self.config = TransformerConfig(**model)
        self._clip_eps = float(clip_eps)
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        tx.append(optax.adam(learning_rate))
        self._opt = optax.chain(*tx)
        params = init_params(self.config, jax.random.PRNGKey(seed))
        self._state = {"params": params,
                       "opt_state": self._opt.init(params)}
        self._jit_grads = jax.jit(self._grads)

    # ------------------------------------------------------ jitted core
    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp
        from ray_tpu.models.transformer import apply
        prompt = batch["prompt"]
        tokens = batch["tokens"]
        P = prompt.shape[1]
        # Teacher-force the whole trajectory in one forward: position
        # P-1+j of the concatenated input predicts generated token j.
        inputs = jnp.concatenate([prompt, tokens[:, :-1]], axis=1)
        logits = apply(self.config, params, inputs)
        gen = logits[:, P - 1:, :].astype(jnp.float32)
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(gen, axis=-1),
            tokens[..., None], axis=-1)[..., 0]
        behavior_lp = batch["logprobs"]
        ratio = jnp.exp(lp - behavior_lp)
        adv = batch["advantages"][:, None]
        clipped = jnp.clip(ratio, 1.0 - self._clip_eps,
                           1.0 + self._clip_eps)
        loss = -jnp.mean(jnp.minimum(ratio * adv, clipped * adv))
        return loss, {"approx_kl": jnp.mean(behavior_lp - lp),
                      "ratio_mean": jnp.mean(ratio)}

    def _grads(self, params, batch):
        import jax
        import optax
        (loss, metrics), grads = jax.value_and_grad(
            self._loss, has_aux=True)(params, batch)
        metrics = dict(metrics, total_loss=loss)
        return grads, metrics, optax.global_norm(grads)

    # --------------------------------------------- Learner protocol
    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        import jax.numpy as jnp
        jbatch = {
            "prompt": jnp.asarray(batch["prompt"], jnp.int32),
            "tokens": jnp.asarray(batch["tokens"], jnp.int32),
            "logprobs": jnp.asarray(batch["logprobs"], jnp.float32),
            "advantages": jnp.asarray(batch["advantages"],
                                      jnp.float32),
        }
        grads, metrics, gnorm = self._jit_grads(
            self._state["params"], jbatch)
        out = {k: float(v) for k, v in metrics.items()}
        out["grad_norm"] = float(gnorm)
        return grads, out

    def apply_gradients(self, grads) -> None:
        import jax
        import jax.numpy as jnp
        import optax
        grads = jax.tree.map(jnp.asarray, grads)
        updates, opt_state = self._opt.update(
            grads, self._state["opt_state"], self._state["params"])
        self._state = {
            "params": optax.apply_updates(self._state["params"],
                                          updates),
            "opt_state": opt_state}

    def get_weights(self):
        import jax
        return jax.tree.map(np.asarray, self._state["params"])

    def set_weights(self, params) -> None:
        import jax
        import jax.numpy as jnp
        self._state["params"] = jax.tree.map(jnp.asarray, params)

    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, float]:
        grads, metrics = self.compute_gradients(batch)
        self.apply_gradients(grads)
        return metrics


class RLHFTrainer:
    """Owns the whole loop: placement → learners → rollout engines →
    weight publisher. One ``train_round()`` call is one PPO round with
    in-flight weight refresh; ``train(n)`` runs n of them."""

    def __init__(self, config: RLHFConfig, slice_manager=None,
                 recorder=None):
        from ray_tpu.rllib.learner import LearnerGroup
        self.config = config
        self.placement = config.lower()
        self._slice_manager = slice_manager
        if slice_manager is not None:
            self.placement.reserve(slice_manager)
        if recorder is None:
            try:
                from ray_tpu.core.global_state import try_global_worker
                w = try_global_worker()
                recorder = getattr(w, "recorder", None)
            except Exception:
                recorder = None
        self._recorder = recorder
        model = config.model_config()
        lr, eps, seed = (config.learning_rate, config.clip_eps,
                         config.seed)

        def make_learner():
            return PolicyLearner(model, learning_rate=lr,
                                 clip_eps=eps, seed=seed)

        self.learners = LearnerGroup(
            make_learner,
            num_learners=(config.num_learners
                          if config.num_learners >= 2 else 0),
            seed=config.seed)
        w0 = self.learners.get_weights()
        # Engines start from the learners' exact initial policy: the
        # version-0 rollouts really are on-policy.
        self.rollout = RolloutEngine(config, params=w0,
                                     recorder=recorder)
        self.publisher = WeightPublisher(
            self.rollout.engines, block_size=config.quant_block_size,
            recorder=recorder)
        self._version = 0       # latest PUBLISHED learner version
        self._version_lock = threading.Lock()
        self._round = 0
        self.history: List[Dict[str, Any]] = []

    # -------------------------------------------------------- prompts
    def round_suffixes(self, round_index: Optional[int] = None
                       ) -> List[List[int]]:
        """Deterministic per-round prompt suffixes (seeded by config
        seed + round): reproducible rollouts without threading prompt
        datasets through every test."""
        cfg = self.config
        rnd = self._round if round_index is None else round_index
        rng = np.random.default_rng(cfg.seed * 1_000_003 + rnd)
        sfx_len = cfg.prompt_len - len(cfg.system_prompt)
        hi = min(1000, int(cfg.model_config().get("vocab_size", 50400)))
        return [rng.integers(2, hi, size=sfx_len,
                             dtype=np.int64).tolist()
                for _ in range(cfg.rollouts_per_round)]

    def _learner_version(self) -> int:
        with self._version_lock:
            return self._version

    # ---------------------------------------------------------- rounds
    def train_round(self, suffixes: Optional[List[List[int]]] = None
                    ) -> Dict[str, Any]:
        cfg = self.config
        self._round += 1
        if suffixes is None:
            suffixes = self.round_suffixes()
        stream = self.rollout.stream_round(
            suffixes, learner_version_fn=self._learner_version,
            collect=True)
        publishes_before = self.publisher.stats()["publishes"]

        def on_round(n_rounds: int, _metrics: Dict[str, float]
                     ) -> None:
            # In-flight republish: engines are still decoding this
            # round's remaining trajectories when this stages weights.
            if n_rounds % cfg.sync_every_updates == 0:
                w = self.learners.get_weights()
                v = self.publisher.publish(w)
                with self._version_lock:
                    self._version = v

        metrics = self.learners.update_from_stream_sharded(
            stream, minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs, on_round=on_round)
        if self.publisher.stats()["publishes"] == publishes_before:
            # Single/local-learner fallback path has no on_round hook:
            # still publish once per round so the loop stays closed.
            w = self.learners.get_weights()
            v = self.publisher.publish(w)
            with self._version_lock:
                self._version = v
        rstats = self.rollout.stats()
        pstats = self.publisher.stats()
        out = dict(metrics)
        out.update({
            "round": self._round,
            "trajectories": len(stream.infos),
            "rollout_tokens": rstats["tokens_total"],
            "prefix_hit_rate": rstats["prefix_hit_rate"],
            "weight_version": rstats["weight_version"],
            "weight_syncs": pstats["publishes"],
            "wire_compression": pstats["compression"],
            "sync_stall_s": rstats["sync_stall_s"],
            "staleness_p50": rstats["staleness_p50"],
            "staleness_p99": rstats["staleness_p99"],
            "staleness_max": rstats["staleness_max"],
        })
        self.history.append(out)
        return out

    def train(self, num_rounds: int) -> List[Dict[str, Any]]:
        return [self.train_round() for _ in range(num_rounds)]

    # ----------------------------------------------------------- audit
    def stats(self) -> Dict[str, Any]:
        return {
            "rounds": self._round,
            "placement": self.placement.placement,
            "slice_strategy": self.placement.slice_strategy,
            "rollout": self.rollout.stats(),
            "publisher": self.publisher.stats(),
        }

    def shutdown(self) -> None:
        try:
            self.rollout.shutdown()
        except Exception:
            pass
        try:
            self.learners.shutdown()
        except Exception:
            pass
        if self._slice_manager is not None:
            self.placement.release(self._slice_manager)
