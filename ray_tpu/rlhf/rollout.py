"""The serving engine as the PPO rollout backend.

Two rollout paths share one trajectory-block schema:

- :class:`RolloutEngine` — in-process ``LLMEngine`` replicas doing
  true continuous batching: every request carries the shared system
  prompt (the radix-trie prefix cache skips re-prefilling it), streams
  ``(token, policy_version, logprob)`` via ``detailed`` submission, and
  tolerates **in-flight weight refresh** — a publish landing mid-round
  changes the version stamps of later tokens of still-decoding
  trajectories, which is exactly what the per-token version column is
  for. Admission of each new trajectory is gated by the
  ``max_weight_lag`` staleness bound.
- :func:`rlhf_rollout_blocks` — a **streaming generator task**
  (``num_returns="streaming"``), deterministic in its arguments
  (engine built from a version-stamped packed weight payload, one
  trajectory at a time, syncs applied at fixed block boundaries), so a
  mid-rollout SIGKILL lineage-replays the block prefix with
  bit-identical tokens AND version stamps, and the owner's dedup
  delivers every block exactly once.

Trajectory blocks are ``(batch, info)`` like env rollout blocks, with
fixed-shape rows: ``prompt (1, P)``, ``tokens/logprobs/versions
(1, T)``, ``advantages (1,)``, ``block_uid (1,)``. Fixed ``T``
(``eos=None``) keeps every learner update at one jitted signature.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.rollout_stream import _concat_batches, _nrows, \
    block_uid
from ray_tpu.rlhf.config import RLHFConfig
from ray_tpu.rlhf.weight_sync import unpack_weights


def _distinct_reward(tokens: List[int]) -> float:
    """Default deterministic sequence reward: distinct-token fraction
    (rewards diverse generations, punishes the degenerate repeats
    greedy decoding of a tiny model loves). Deterministic in the
    trajectory, so lineage replay reproduces advantages exactly."""
    return len(set(tokens)) / max(1, len(tokens))


class LocalBlockStream:
    """Queue-fed twin of ``RolloutBlockStream`` for in-process
    producers: same consume edge (``iter_blocks`` / ``iter_batches`` /
    ``full_batch`` / bubble accounting), fed by ``push`` from the
    rollout drain threads instead of ``wait_any`` over generators."""

    _SENTINEL = object()

    def __init__(self, collect: bool = False):
        self._q: "queue.Queue" = queue.Queue()
        self._collect = collect
        self.blocks: List[Dict[str, np.ndarray]] = []
        self.infos: List[Dict[str, Any]] = []
        self._wait_s = 0.0
        self._wall_t0: Optional[float] = None
        self._wall_s = 0.0
        self._rows = 0
        self._err: Optional[BaseException] = None

    # ---------------------------------------------------- producer edge
    def push(self, batch: Dict[str, np.ndarray],
             info: Dict[str, Any]) -> None:
        self._q.put((batch, info))

    def finish(self, err: Optional[BaseException] = None) -> None:
        self._err = err
        self._q.put(self._SENTINEL)

    # ---------------------------------------------------- consumer edge
    def iter_blocks(self, timeout: float = 600.0
                    ) -> Iterator[Tuple[Dict[str, np.ndarray],
                                        Dict[str, Any]]]:
        if self._wall_t0 is None:
            self._wall_t0 = time.perf_counter()
        deadline = time.monotonic() + timeout
        while True:
            t0 = time.perf_counter()
            try:
                item = self._q.get(timeout=1.0)
            except queue.Empty:
                self._wait_s += time.perf_counter() - t0
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        "no rollout block arrived before the deadline")
                continue
            self._wait_s += time.perf_counter() - t0
            if item is self._SENTINEL:
                break
            batch, info = item
            self._rows += _nrows(batch)
            if self._collect:
                self.blocks.append(batch)
            self.infos.append(info)
            yield batch, info
        self._wall_s = time.perf_counter() - self._wall_t0
        if self._err is not None:
            raise self._err

    def iter_batches(self, batch_size: Optional[int] = None,
                     drop_last: bool = False
                     ) -> Iterator[Dict[str, np.ndarray]]:
        carry: List[Dict[str, np.ndarray]] = []
        carry_rows = 0
        for batch, _info in self.iter_blocks():
            if batch_size is None:
                yield batch
                continue
            carry.append(batch)
            carry_rows += _nrows(batch)
            while carry_rows >= batch_size:
                merged = _concat_batches(carry)
                n = _nrows(merged)
                yield {k: v[:batch_size] for k, v in merged.items()}
                rest = {k: v[batch_size:] for k, v in merged.items()}
                carry = [rest] if n > batch_size else []
                carry_rows = n - batch_size
        if batch_size is not None and carry_rows and not drop_last:
            yield _concat_batches(carry)

    def full_batch(self) -> Dict[str, np.ndarray]:
        if not self.blocks:
            raise ValueError("no blocks collected "
                             "(construct with collect=True)")
        return _concat_batches(self.blocks)

    def delivered_uids(self) -> List[int]:
        return [info["uid"] for info in self.infos]

    def stats(self) -> Dict[str, float]:
        wall = self._wall_s or (
            time.perf_counter() - self._wall_t0
            if self._wall_t0 is not None else 0.0)
        return {
            "rows": self._rows,
            "blocks": len(self.infos),
            "wait_s": round(self._wait_s, 4),
            "wall_s": round(wall, 4),
            "bubble": round(self._wait_s / wall, 4) if wall > 0
            else 0.0,
        }

    def close(self) -> None:
        pass


class RolloutEngine:
    """The generation side of PPO over a fleet of in-process serving
    engines (the anakin path; sebulba's remote twin is the
    :func:`rlhf_rollout_blocks` generator-task fleet).

    Every trajectory request is ``system_prompt + suffix`` — the radix
    trie serves the shared prefix from cache after the first request
    per engine, so rollout prefill cost is ~one suffix per trajectory.
    ``stream_round`` admits trajectories under the staleness gate and
    streams completed trajectory blocks in completion order.
    """

    def __init__(self, config: RLHFConfig, params=None,
                 recorder=None):
        import jax
        import jax.numpy as jnp
        from ray_tpu.models import TransformerConfig, init_params
        from ray_tpu.serve.llm_engine import (EngineConfig, LLMEngine,
                                              _resolve_dtype)
        self.config = config
        model = config.model_config()
        model["dtype"] = _resolve_dtype(model["dtype"])
        self.model_config = TransformerConfig(**model)
        ec = EngineConfig(**config.engine_config())
        if params is None:
            params = init_params(self.model_config,
                                 jax.random.PRNGKey(config.seed))
        params = jax.tree.map(jnp.asarray, params)
        self.engines = [
            LLMEngine(self.model_config, ec, params=params,
                      replica_tag=f"rlhf-engine-{i}")
            for i in range(config.num_engines)]
        self._recorder = recorder
        self._lock = threading.Lock()
        self._seq = 0                  # global trajectory counter
        self._round = 0
        self._staleness: List[int] = []
        self._baseline: Optional[float] = None
        self.reward_fn: Callable[[List[int]], float] = _distinct_reward
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            config.num_engines * ec.decode_slots + 4,
            thread_name_prefix="rlhf-rollout")

    # ----------------------------------------------------------- state
    @property
    def weight_version(self) -> int:
        """Slowest engine's policy version (the staleness gate's
        denominator — admission waits for the laggard)."""
        return min(e.weight_version for e in self.engines)

    # ----------------------------------------------------------- round
    def stream_round(self, suffixes: List[List[int]],
                     learner_version_fn: Optional[Callable[[], int]]
                     = None,
                     collect: bool = False,
                     admit_timeout_s: float = 60.0
                     ) -> LocalBlockStream:
        """Launch one rollout round; returns the block stream
        immediately (blocks arrive in completion order). Each
        trajectory is admitted to its engine only while
        ``learner_version - engine_version <= max_weight_lag``; the
        observed lag at admission is the round's staleness sample
        set."""
        stream = LocalBlockStream(collect=collect)
        self._pool.submit(self._feed_round, list(suffixes),
                          learner_version_fn, admit_timeout_s, stream)
        return stream

    def _feed_round(self, suffixes, learner_version_fn,
                    admit_timeout_s, stream) -> None:
        cfg = self.config
        try:
            self._round += 1
            rnd = self._round
            futs = []
            for j, suffix in enumerate(suffixes):
                eng = self.engines[j % len(self.engines)]
                if learner_version_fn is not None:
                    deadline = time.monotonic() + admit_timeout_s
                    while (learner_version_fn() - eng.weight_version
                           > cfg.max_weight_lag):
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                "staleness gate starved: engine never "
                                "caught up within max_weight_lag="
                                f"{cfg.max_weight_lag}")
                        time.sleep(0.002)
                    lag = max(0, learner_version_fn()
                              - eng.weight_version)
                else:
                    lag = 0
                with self._lock:
                    self._staleness.append(lag)
                    seq = self._seq
                    self._seq += 1
                prompt = list(cfg.system_prompt) + [int(t)
                                                    for t in suffix]
                # every trajectory is a traced serve request: the
                # engine keeps its own 1-in-N tail sample, but the
                # round's RLHF_ROLLOUT event names the slowest
                # trajectory's request_id so `ray-tpu trace` can open
                # its waterfall from the flight recorder
                from ray_tpu.serve.request_trace import new_request_id
                rid = new_request_id()
                req = eng.submit(prompt, cfg.max_new_tokens,
                                 eos_token_id=None, detailed=True,
                                 trace_ctx={"request_id": rid,
                                            "policy": "rlhf",
                                            "admission": "bypass",
                                            "enqueue_ts": time.time()})
                futs.append(self._pool.submit(
                    self._drain, j % len(self.engines), seq, prompt,
                    req, eng, stream, rid))
            tokens = 0
            versions: set = set()
            slowest_rid, slowest_s = None, -1.0
            for f in futs:
                n_tok, vers, rid, dur_s = f.result()
                tokens += n_tok
                versions |= vers
                if dur_s > slowest_s:
                    slowest_rid, slowest_s = rid, dur_s
            if self._recorder is not None:
                try:
                    self._recorder.record(
                        "RLHF_ROLLOUT", round=rnd,
                        trajectories=len(suffixes), tokens=tokens,
                        policy_versions=sorted(versions),
                        slowest_request_id=slowest_rid,
                        slowest_s=round(max(slowest_s, 0.0), 6))
                except Exception:
                    pass
            stream.finish()
        except BaseException as e:  # noqa: BLE001 — surface, never hang
            stream.finish(err=e)

    def _drain(self, engine_idx: int, seq: int, prompt: List[int],
               req, eng, stream: LocalBlockStream,
               request_id: Optional[str] = None
               ) -> Tuple[int, set, Optional[str], float]:
        from ray_tpu.serve.llm_engine import _DONE, EngineDeadError
        t_start = time.monotonic()
        toks: List[int] = []
        vers: List[int] = []
        lps: List[float] = []
        while True:
            try:
                item = req.out.get(timeout=0.5)
            except queue.Empty:
                if eng._dead is not None:
                    raise EngineDeadError(
                        f"engine step loop died: {eng._dead!r}")
                continue
            if item is _DONE:
                break
            if isinstance(item, BaseException):
                raise item
            tok, ver, lp = item
            toks.append(int(tok))
            vers.append(int(ver))
            lps.append(float(lp) if lp is not None else 0.0)
        T = self.config.max_new_tokens
        if len(toks) != T:
            raise RuntimeError(
                f"trajectory {seq} has {len(toks)} tokens, expected "
                f"{T} (fixed-length rollouts need eos=None)")
        reward = float(self.reward_fn(toks))
        with self._lock:
            base = self._baseline if self._baseline is not None \
                else reward
            adv = reward - base
            self._baseline = 0.9 * base + 0.1 * reward
        uid = block_uid(engine_idx, seq)
        batch = {
            "prompt": np.asarray([prompt], np.int32),
            "tokens": np.asarray([toks], np.int32),
            "logprobs": np.asarray([lps], np.float32),
            "versions": np.asarray([vers], np.int32),
            "advantages": np.asarray([adv], np.float32),
            "block_uid": np.full((1,), uid, np.int64),
        }
        info = {"uid": uid, "worker_index": engine_idx,
                "shard_key": seq, "block": seq, "reward": reward,
                "versions": sorted(set(vers)),
                "request_id": request_id}
        stream.push(batch, info)
        return T, set(vers), request_id, time.monotonic() - t_start

    # ----------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        eng = [e.stats() for e in self.engines]
        with self._lock:
            lags = list(self._staleness)
            n_traj = self._seq
        hits = sum(s["prefix_hit_blocks_total"] for s in eng)
        blocks = sum(s["prompt_blocks_total"] for s in eng)
        return {
            "trajectories": n_traj,
            "tokens_total": sum(s["tokens_total"] for s in eng),
            "prefix_hit_rate": (round(hits / blocks, 4) if blocks
                                else None),
            "weight_version": self.weight_version,
            "weight_swaps": sum(s["weight_swaps"] for s in eng),
            "weight_swap_wall_s": round(
                sum(s["weight_swap_wall_s"] for s in eng), 6),
            "sync_stall_s": round(
                sum(s["sync_stall_s"] for s in eng), 6),
            "staleness_samples": len(lags),
            "staleness_p50": (float(np.percentile(lags, 50))
                              if lags else None),
            "staleness_p99": (float(np.percentile(lags, 99))
                              if lags else None),
            "staleness_max": max(lags) if lags else None,
            "engines": eng,
        }

    def pool_audit(self) -> List[str]:
        out: List[str] = []
        for i, e in enumerate(self.engines):
            out.extend(f"engine{i}: {line}" for line in e.pool_audit())
        return out

    def shutdown(self) -> None:
        for e in self.engines:
            try:
                e.shutdown()
            except Exception:
                pass
        self._pool.shutdown(wait=False)


# ------------------------------------------------- generator-task path
def rlhf_rollout_blocks(model: Dict[str, Any], engine: Dict[str, Any],
                        packed_weights: Dict[str, Any],
                        suffixes: List[List[int]],
                        system_prompt: List[int],
                        max_new_tokens: int,
                        worker_index: int = 0,
                        syncs: Optional[Dict[int, Dict[str, Any]]]
                        = None,
                        fault: Optional[Dict[str, Any]] = None):
    """Generator-task body for the disaggregated (sebulba) rollout
    fleet: build a private engine from the version-stamped int8 packed
    weights, generate one trajectory per suffix, and yield ``(batch,
    info)`` blocks. Deterministic in its arguments — greedy decode from
    packed weights, ``syncs`` (block index → packed payload) applied at
    fixed block boundaries and *awaited* before the next trajectory —
    so a SIGKILL mid-round lineage-replays the prefix with identical
    tokens and identical per-token version stamps, and the streaming
    owner's dedup delivers each block exactly once.

    ``fault={"die_at_block": i, "marker": path}`` is the same chaos
    hook ``rollout_stream`` carries: first execution SIGKILLs its own
    worker right before yielding block ``i``."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.models import TransformerConfig
    from ray_tpu.serve.llm_engine import (EngineConfig, LLMEngine,
                                          _resolve_dtype)
    model = dict(model)
    model["dtype"] = _resolve_dtype(model.get("dtype", "float32"))
    ec = dict(engine)
    ec["capture_logprobs"] = True
    ec["spec_tokens"] = 0
    params, version = unpack_weights(packed_weights)
    eng = LLMEngine(TransformerConfig(**model), EngineConfig(**ec),
                    params=jax.tree.map(jnp.asarray, params),
                    replica_tag=f"rlhf-gen-{worker_index}")
    eng.stage_weights(jax.tree.map(jnp.asarray, params), version)

    def _await_version(v: int, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while eng.stats()["weight_version"] != v:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"weight swap to version {v} never landed")
            time.sleep(0.002)

    _await_version(version)
    baseline: Optional[float] = None
    try:
        for b, suffix in enumerate(suffixes):
            if syncs and b in syncs:
                p2, v2 = unpack_weights(syncs[b])
                eng.stage_weights(jax.tree.map(jnp.asarray, p2), v2)
                _await_version(v2)
            if fault and b == fault.get("die_at_block"):
                import os
                marker = fault.get("marker")
                if marker and not os.path.exists(marker):
                    open(marker, "w").close()
                    os.kill(os.getpid(),
                            __import__("signal").SIGKILL)
            prompt = [int(t) for t in system_prompt] + \
                [int(t) for t in suffix]
            from ray_tpu.serve.request_trace import new_request_id
            rid = new_request_id()
            items = list(eng.generate_sync(
                prompt, max_new_tokens, eos_token_id=None,
                detailed=True,
                trace_ctx={"request_id": rid, "policy": "rlhf",
                           "admission": "bypass",
                           "enqueue_ts": time.time()}))
            toks = [int(t) for t, _v, _l in items]
            vers = [int(v) for _t, v, _l in items]
            lps = [float(l) if l is not None else 0.0
                   for _t, _v, l in items]
            reward = _distinct_reward(toks)
            base = baseline if baseline is not None else reward
            adv = reward - base
            baseline = 0.9 * base + 0.1 * reward
            uid = block_uid(worker_index, b)
            batch = {
                "prompt": np.asarray([prompt], np.int32),
                "tokens": np.asarray([toks], np.int32),
                "logprobs": np.asarray([lps], np.float32),
                "versions": np.asarray([vers], np.int32),
                "advantages": np.asarray([adv], np.float32),
                "block_uid": np.full((1,), uid, np.int64),
            }
            info = {"uid": uid, "worker_index": worker_index,
                    "block": b, "reward": reward,
                    "versions": sorted(set(vers))}
            yield batch, info
    finally:
        eng.shutdown()


_rlhf_stream_remote = None


def _remote_rlhf_stream():
    global _rlhf_stream_remote
    if _rlhf_stream_remote is None:
        _rlhf_stream_remote = ray_tpu.remote(
            num_cpus=1, num_returns="streaming")(rlhf_rollout_blocks)
    return _rlhf_stream_remote


def make_rlhf_rollout_streams(model: Dict[str, Any],
                              engine: Dict[str, Any],
                              packed_weights: Dict[str, Any],
                              suffixes_per_worker: List[List[List[int]]],
                              system_prompt: List[int],
                              max_new_tokens: int, *,
                              backpressure: int = 4,
                              syncs: Optional[Dict[int, Dict]] = None,
                              faults: Optional[Dict[int, Dict]] = None
                              ) -> List[Any]:
    """Launch one :func:`rlhf_rollout_blocks` generator task per
    worker; returns their ``ObjectRefGenerator``s (feed them to
    ``RolloutBlockStream`` for ``wait_any`` fan-in). ``syncs`` /
    ``faults`` map worker_index → per-worker dicts."""
    fn = _remote_rlhf_stream()
    return [
        fn.options(generator_backpressure_num_objects=backpressure)
        .remote(model, engine, packed_weights, sfx, system_prompt,
                max_new_tokens, i, (syncs or {}).get(i),
                (faults or {}).get(i))
        for i, sfx in enumerate(suffixes_per_worker)]
