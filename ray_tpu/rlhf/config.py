"""RLHFConfig: one declarative knob set for the whole RLHF loop.

``placement`` names the Podracer split (arXiv:2104.06272):

- ``"anakin"`` — learners and rollout engines **colocated** on one TPU
  slice (SLICE_PACK): weight sync crosses shared memory, rollout and
  update phases time-share the chips. Best for small models / short
  rollouts where transfer dominates.
- ``"sebulba"`` — **disaggregated** fleets (SLICE_SPREAD): the rollout
  engines own their slice(s) and decode continuously while the learner
  slice trains; weight refresh ships over the int8 wire and lands
  between decode steps. Best when generation is the bottleneck.

Lowering is a one-line choice: :meth:`RLHFConfig.lower` returns an
:class:`RLHFPlacement` whose ``learner_plan`` / ``slice_strategy`` feed
the existing ``ParallelPlan`` / ``SliceManager`` machinery, and whose
``reserve(slice_manager)`` acquires the slice set the placement implies
(one shared slice packed, separate rollout + train slices spread).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

PLACEMENTS = ("anakin", "sebulba")


@dataclasses.dataclass(frozen=True)
class RLHFConfig:
    """Knobs of the closed PPO-RLHF loop (see README "RLHF").

    - ``placement``: Podracer split — ``"anakin"`` (colocated,
      SLICE_PACK) or ``"sebulba"`` (disaggregated, SLICE_SPREAD).
    - ``num_learners``: learner replicas; >= 2 activates the sharded
      streaming epoch (every learner trains as blocks arrive).
    - ``num_engines``: rollout engine replicas.
    - ``rollouts_per_round``: trajectories generated per PPO round.
    - ``max_new_tokens``: fixed trajectory length (uniform shapes keep
      the learner's jitted update at ONE compiled signature).
    - ``system_prompt``: shared prompt prefix every rollout request
      carries — exactly the high-hit-rate workload the radix-trie
      prefix cache serves (hit rate is asserted by the e2e).
    - ``prompt_len``: total prompt length (system + per-request
      suffix), fixed so trajectory batches concatenate.
    - ``max_weight_lag``: staleness bound — a new rollout request is
      admitted only while ``learner_version - engine_version <= lag``.
    - ``sync_every_updates``: publish fresh weights to the engines
      after every N learner rounds (in flight — decode never stops).
    - ``quant_block_size``: int8 wire block size for weight sync.
    """
    placement: str = "anakin"
    num_learners: int = 2
    num_engines: int = 1
    rollouts_per_round: int = 8
    max_new_tokens: int = 16
    system_prompt: Tuple[int, ...] = tuple(range(2, 50))
    prompt_len: int = 56
    max_weight_lag: int = 1
    sync_every_updates: int = 1
    quant_block_size: int = 256
    minibatch_size: int = 4
    num_epochs: int = 1
    learning_rate: float = 1e-3
    clip_eps: float = 0.2
    seed: int = 0
    model: Optional[Dict[str, Any]] = None
    engine: Optional[Dict[str, Any]] = None
    slice_type: str = "pod"

    def __post_init__(self):
        if self.placement not in PLACEMENTS:
            raise ValueError(
                f"placement must be one of {PLACEMENTS}, "
                f"got {self.placement!r}")
        if min(self.num_learners, self.num_engines,
               self.rollouts_per_round, self.max_new_tokens) < 1:
            raise ValueError(
                "num_learners/num_engines/rollouts_per_round/"
                f"max_new_tokens must be >= 1, got {self}")
        if self.max_weight_lag < 0:
            raise ValueError("max_weight_lag must be >= 0")
        if not self.system_prompt:
            raise ValueError(
                "system_prompt must be non-empty (the shared prefix is "
                "what the radix trie amortizes across rollouts)")
        if self.prompt_len < len(self.system_prompt) + 1:
            raise ValueError(
                f"prompt_len={self.prompt_len} must leave room for at "
                f"least one suffix token after the "
                f"{len(self.system_prompt)}-token system prompt")

    # ------------------------------------------------------- lowering
    @property
    def slice_strategy(self) -> str:
        """SLICE_PACK (anakin, colocated) / SLICE_SPREAD (sebulba)."""
        return "SLICE_PACK" if self.placement == "anakin" \
            else "SLICE_SPREAD"

    def learner_plan(self):
        """The learner fleet's ``ParallelPlan``: dp across learners,
        carrying this placement's slice strategy down to the gang
        scheduler."""
        from ray_tpu.parallel.plan import ParallelPlan
        return ParallelPlan(dp=max(1, self.num_learners),
                            slice_strategy=self.slice_strategy)

    def lower(self) -> "RLHFPlacement":
        """Clusterless lowering: which slices the placement wants and
        how the fleets map onto them (reserve() makes it live)."""
        if self.placement == "anakin":
            groups = [{"role": "shared", "engines": self.num_engines,
                       "learners": self.num_learners}]
        else:
            groups = [{"role": "rollout", "engines": self.num_engines,
                       "learners": 0},
                      {"role": "train", "engines": 0,
                       "learners": self.num_learners}]
        return RLHFPlacement(placement=self.placement,
                             slice_strategy=self.slice_strategy,
                             slice_type=self.slice_type,
                             groups=groups)

    def engine_config(self) -> Dict[str, Any]:
        """Engine knob dict with the RLHF invariants folded in:
        logprob capture on (the rollout payload), prefix sharing on
        (the system prompt is the whole point), speculation off
        (incompatible with capture), window sized to fit prompt +
        trajectory."""
        ec = dict(self.engine or {})
        ec["capture_logprobs"] = True
        ec["spec_tokens"] = 0
        ec.setdefault("enable_prefix_sharing", True)
        need = self.prompt_len + self.max_new_tokens + 2
        if ec.get("max_seq_len", 0) < need:
            ec["max_seq_len"] = need
        ec.setdefault("max_new_tokens", self.max_new_tokens)
        return ec

    def model_config(self) -> Dict[str, Any]:
        m = dict(self.model or {})
        m.setdefault("dtype", "float32")
        return m


@dataclasses.dataclass
class RLHFPlacement:
    """A lowered placement: one bundle group per slice the placement
    wants. ``reserve`` acquires them through a live ``SliceManager``
    (all-or-nothing: a partial acquisition is rolled back so a
    half-placed loop never runs split-brain); clusterless callers just
    read ``groups``/``slice_strategy``."""
    placement: str
    slice_strategy: str
    slice_type: str
    groups: List[Dict[str, Any]]
    slice_ids: List[str] = dataclasses.field(default_factory=list)

    @property
    def num_slices(self) -> int:
        return len(self.groups)

    def reserve(self, slice_manager, timeout_s: float = 60.0
                ) -> List[str]:
        acquired: List[str] = []
        for g in self.groups:
            sid = slice_manager.acquire_slice(self.slice_type)
            if sid is None or not slice_manager.wait_until_up(
                    sid, timeout_s=timeout_s):
                for s in acquired:
                    try:
                        slice_manager.drain_slice(
                            s, reason="rlhf placement rollback")
                    except Exception:
                        pass
                raise RuntimeError(
                    f"could not reserve {self.num_slices} "
                    f"{self.slice_type!r} slice(s) for the "
                    f"{self.placement!r} placement")
            g["slice_id"] = sid
            acquired.append(sid)
        self.slice_ids = acquired
        return acquired

    def release(self, slice_manager) -> None:
        for sid in self.slice_ids:
            try:
                slice_manager.drain_slice(sid, reason="rlhf shutdown")
            except Exception:
                pass
        self.slice_ids = []
