"""Disaggregated RLHF: serve-engine rollouts, multi-learner streams,
in-flight int8 weight sync (ROADMAP item 1 — the flagship composition).

The closed loop, wired through every existing layer:

- :mod:`ray_tpu.rlhf.config` — ``RLHFConfig`` names the Podracer
  placement (``anakin`` colocated / ``sebulba`` disaggregated,
  arXiv:2104.06272) and lowers it to SLICE_PACK / SLICE_SPREAD through
  ``ParallelPlan`` / ``SliceManager``.
- :mod:`ray_tpu.rlhf.rollout` — the serving engine as the PPO rollout
  backend: shared-system-prompt requests ride the radix-trie prefix
  cache, completions stream back as trajectory blocks carrying
  ``(token, policy_version, logprob)``.
- :mod:`ray_tpu.rlhf.weight_sync` — learner→engine parameter refresh
  over the int8 blockwise wire (``parallel.quantization``), applied
  between decode steps by a double-buffered pointer swap: decode never
  drains (MindSpeed-RL's headline trick, arXiv:2507.19017).
- :mod:`ray_tpu.rlhf.trainer` — ``RLHFTrainer`` closes the loop:
  rollout rounds feed a multi-learner ``LearnerGroup`` through sharded
  streaming epoch-1 updates, with weights republished in flight under
  a ``max_weight_lag`` staleness bound on rollout admission.
"""

from ray_tpu.rlhf.config import RLHFConfig, RLHFPlacement
from ray_tpu.rlhf.rollout import (LocalBlockStream, RolloutEngine,
                                  make_rlhf_rollout_streams,
                                  rlhf_rollout_blocks)
from ray_tpu.rlhf.trainer import PolicyLearner, RLHFTrainer
from ray_tpu.rlhf.weight_sync import (WeightPublisher, pack_weights,
                                      packed_wire_bytes, unpack_weights)

__all__ = [
    "RLHFConfig", "RLHFPlacement", "RolloutEngine", "LocalBlockStream",
    "rlhf_rollout_blocks", "make_rlhf_rollout_streams", "RLHFTrainer",
    "PolicyLearner", "WeightPublisher", "pack_weights",
    "unpack_weights", "packed_wire_bytes",
]
