"""@remote functions.

Equivalent of the reference's ``python/ray/remote_function.py`` (:262
``_remote`` → ``core_worker.submit_task``). The function body is pickled
once and exported to the controller's function store keyed by descriptor
(reference: ``_private/function_manager.py``); submissions carry only the
key.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core.global_state import global_worker
from ray_tpu.core.ids import TaskID


def _client_route():
    """The installed ray:// ClientWorker iff client mode is active AND
    no local runtime exists (a local runtime always wins)."""
    from ray_tpu.core.global_state import try_global_worker
    if try_global_worker() is not None:
        return None
    from ray_tpu import api
    return api._client_or_none()
from ray_tpu.core.task_spec import FunctionDescriptor, SchedulingStrategy, TaskSpec


def _prepare_env(w, env):
    """Package working_dir/py_modules into the session cache before the
    spec ships (reference: runtime-env agent URI creation)."""
    if not env:
        return env
    from ray_tpu.core.runtime_env import prepare_runtime_env
    return prepare_runtime_env(env, w.session_dir)


_DEFAULT_OPTS = dict(
    num_cpus=1.0, num_tpus=0.0, resources=None, num_returns=1,
    max_retries=3, retry_exceptions=False, name=None,
    scheduling_strategy=None, runtime_env=None, memory=None,
    placement_group=None, placement_group_bundle_index=-1,
    generator_backpressure_num_objects=None,
)


def make_scheduling_strategy(opts: Dict[str, Any]) -> SchedulingStrategy:
    strat = opts.get("scheduling_strategy")
    if isinstance(strat, SchedulingStrategy):
        return strat
    if strat == "SPREAD":
        return SchedulingStrategy(kind="SPREAD")
    if strat == "DEFAULT" or strat is None:
        pg = opts.get("placement_group")
        if pg is not None:
            return SchedulingStrategy(
                kind="PLACEMENT_GROUP", placement_group_id=pg.id,
                placement_group_bundle_index=opts.get(
                    "placement_group_bundle_index", -1))
        return SchedulingStrategy()
    # user objects from ray_tpu.util.scheduling_strategies convert themselves
    if hasattr(strat, "to_internal"):
        return strat.to_internal()
    raise ValueError(f"bad scheduling_strategy: {strat!r}")


def resources_from_opts(opts: Dict[str, Any]) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    ncpu = opts.get("num_cpus")
    if ncpu:
        res["CPU"] = float(ncpu)
    ntpu = opts.get("num_tpus") or opts.get("num_gpus")  # num_gpus alias
    if ntpu:
        res["TPU"] = float(ntpu)
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return res


class RemoteFunction:
    def __init__(self, fn, **options):
        self._function = fn
        self._opts = dict(_DEFAULT_OPTS)
        self._opts.update(options)
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)
        self._pickled: Optional[bytes] = None
        self._descriptor: Optional[FunctionDescriptor] = None
        self._exported_sessions = set()

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote().")

    def options(self, **overrides) -> "RemoteFunction":
        rf = RemoteFunction(self._function, **{**self._opts, **overrides})
        rf._pickled = self._pickled
        rf._descriptor = self._descriptor
        rf._exported_sessions = self._exported_sessions
        return rf

    def _ensure_exported(self, w) -> FunctionDescriptor:
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._function)
            h = hashlib.sha1(self._pickled).hexdigest()[:16]
            self._descriptor = FunctionDescriptor(
                module=getattr(self._function, "__module__", "") or "",
                qualname=getattr(self._function, "__qualname__", self.__name__),
                function_hash=h)
        key = self._descriptor.key()
        sid = id(w)
        if sid not in self._exported_sessions:
            w.export_function(key, self._pickled)
            self._exported_sessions.add(sid)
        return self._descriptor

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._opts)

    def _remote(self, args, kwargs, opts):
        client = _client_route()
        if client is not None:
            # decorated before ray_tpu.init("ray://..."): route through
            # the client at call time (reference: client-mode hooks)
            if getattr(self, "_client_fn", None) is None:
                self._client_fn = client._wrap(
                    self._function,
                    {k: v for k, v in opts.items() if v is not None})
            return self._client_fn.remote(*args, **kwargs)
        w = global_worker()
        descriptor = self._ensure_exported(w)
        args_blob, arg_refs, _ = w.serialize_args(args, kwargs)
        # resources/strategy are pure functions of opts — compute once
        # per opts object, not per call (fan-out submit hot path). The
        # resources dict is copied into each spec (specs outlive the
        # call in _inflight_specs; a shared mutable dict would be a
        # corruption hazard); the strategy instance is shared and
        # treated as a read-only descriptor downstream.
        cache = getattr(self, "_opts_cache", None)
        if cache is None or cache[0] is not opts:
            cache = (opts, resources_from_opts(opts),
                     make_scheduling_strategy(opts))
            self._opts_cache = cache
        num_returns = opts["num_returns"]
        # num_returns="streaming": a generator task — items become their
        # own objects, reported while the task runs; the call returns an
        # ObjectRefGenerator (reference: ray.remote num_returns model)
        streaming = num_returns == "streaming"
        from ray_tpu.core.task_spec import STREAMING_RETURNS
        spec = TaskSpec(
            task_id=w.next_task_id(),
            job_id=w.job_id,
            function=descriptor,
            args_blob=args_blob,
            arg_refs=[(i, oid) for i, oid in arg_refs],
            num_returns=STREAMING_RETURNS if streaming else num_returns,
            resources=dict(cache[1]),
            scheduling_strategy=cache[2],
            max_retries=opts["max_retries"],
            retry_exceptions=bool(opts["retry_exceptions"]),
            name=opts.get("name") or self.__name__,
            runtime_env=_prepare_env(w, opts.get("runtime_env")),
            backpressure=int(
                opts.get("generator_backpressure_num_objects") or 0),
        )
        if streaming:
            return w.submit_streaming_task(spec)
        refs = w.submit_task(spec)
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """DAG API entry (reference: python/ray/dag/function_node.py)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs)
