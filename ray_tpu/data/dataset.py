"""Dataset: lazy, streaming, distributed data.

Reference: ``python/ray/data/dataset.py:137`` (``map_batches`` :371,
``iter_batches`` :3640, ``materialize`` :4520, ``streaming_split``).
Blocks are Arrow tables in the object store; transforms are lazy logical
ops executed by the fused streaming executor (``_internal/plan.py``).
TPU-first notes: this layer is host-side CPU work; ``iter_batches``
yields numpy dicts sized for one ``jax.device_put`` per step, and
``streaming_split`` feeds one shard per TPU-host worker.
"""

from __future__ import annotations

import functools
import itertools
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Union)

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import (
    Block, BlockAccessor, BlockMetadata, _to_table)
from ray_tpu.data.context import DataContext
from ray_tpu.data._internal.plan import (
    AllToAllOp, ExchangeOp, ExecutionPlan, InputDataOp, LimitOp,
    OneToOneOp, ReadOp,
    UnionOp, execute_streaming)
from ray_tpu.data._internal import shuffle as shuffle_mod


class ActorPoolStrategy:
    """compute= for map_batches (reference ``ActorPoolStrategy``)."""

    def __init__(self, size: Optional[int] = None,
                 min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.size = size or max_size or min_size or 2


def _batched(table: pa.Table, batch_size: Optional[int]
             ) -> Iterator[pa.Table]:
    if batch_size is None or table.num_rows <= batch_size:
        yield table
        return
    for start in range(0, table.num_rows, batch_size):
        yield table.slice(start, batch_size)


def _make_map_batches_block_fn(fn, batch_size, batch_format, fn_args,
                               fn_kwargs):
    def block_fn(block: Block, instance=None) -> Block:
        call = instance if instance is not None else fn
        outs = []
        for sub in _batched(block, batch_size):
            batch = BlockAccessor(sub).to_batch(batch_format)
            out = call(batch, *fn_args, **fn_kwargs)
            outs.append(_to_table(out))
        return BlockAccessor.concat(outs)
    return block_fn


class Dataset:
    def __init__(self, plan: ExecutionPlan):
        self._plan = plan

    # ------------------------------------------------------ transforms
    def map_batches(self, fn, *, batch_size: Optional[int] = 1024,
                    batch_format: Optional[str] = None,
                    compute: Optional[ActorPoolStrategy] = None,
                    fn_args: tuple = (), fn_kwargs: Optional[dict] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    num_cpus: Optional[float] = None,
                    **_ignored) -> "Dataset":
        """Reference ``dataset.py:371``. ``fn`` maps a batch (numpy dict
        by default) to a batch; a callable CLASS runs on an actor pool
        with per-actor construction."""
        ctx = DataContext.get_current()
        batch_format = batch_format or ctx.default_batch_format
        fn_kwargs = fn_kwargs or {}
        is_class = isinstance(fn, type)
        name = f"MapBatches({getattr(fn, '__name__', 'fn')})"
        if is_class and compute is None:
            compute = ActorPoolStrategy(size=2)
        ctor = None
        if is_class:
            ckw = fn_constructor_kwargs or {}
            cargs = fn_constructor_args
            cls = fn
            ctor = lambda: cls(*cargs, **ckw)  # noqa: E731
            fn = None
        block_fn = _make_map_batches_block_fn(
            fn, batch_size, batch_format, fn_args, fn_kwargs)
        op = OneToOneOp(
            block_fn, name=name,
            actor_pool_size=compute.size if compute else None,
            fn_constructor=ctor,
            num_cpus=num_cpus)
        return Dataset(self._plan.with_op(op))

    def map(self, fn, **kwargs) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            # Empty input: keep the input schema rather than degrading
            # to a zero-column table (the output schema is unknowable
            # without rows, and downstream concat promotes).
            return pa.Table.from_pylist(rows) if rows \
                else block.schema.empty_table()
        return Dataset(self._plan.with_op(
            OneToOneOp(block_fn, name="Map")))

    def flat_map(self, fn, **kwargs) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows = [o for r in BlockAccessor(block).iter_rows()
                    for o in fn(r)]
            return pa.Table.from_pylist(rows) if rows \
                else block.schema.empty_table()
        return Dataset(self._plan.with_op(
            OneToOneOp(block_fn, name="FlatMap")))

    def filter(self, fn, **kwargs) -> "Dataset":
        def block_fn(block: Block) -> Block:
            rows = [r for r in BlockAccessor(block).iter_rows() if fn(r)]
            return (pa.Table.from_pylist(rows) if rows
                    else block.schema.empty_table())
        return Dataset(self._plan.with_op(
            OneToOneOp(block_fn, name="Filter")))

    def select_columns(self, cols: List[str], **kwargs) -> "Dataset":
        return Dataset(self._plan.with_op(OneToOneOp(
            lambda b: BlockAccessor(b).select(cols), name="Select")))

    def drop_columns(self, cols: List[str], **kwargs) -> "Dataset":
        def block_fn(b: Block) -> Block:
            keep = [c for c in b.column_names if c not in cols]
            return BlockAccessor(b).select(keep)
        return Dataset(self._plan.with_op(OneToOneOp(block_fn, name="Drop")))

    def add_column(self, name: str, fn, **kwargs) -> "Dataset":
        def block_fn(b: Block) -> Block:
            df = BlockAccessor(b).to_pandas()
            df[name] = fn(df)
            return _to_table(df)
        return Dataset(self._plan.with_op(
            OneToOneOp(block_fn, name="AddColumn")))

    def rename_columns(self, mapping: Dict[str, str], **kwargs) -> "Dataset":
        def block_fn(b: Block) -> Block:
            return b.rename_columns(
                [mapping.get(c, c) for c in b.column_names])
        return Dataset(self._plan.with_op(
            OneToOneOp(block_fn, name="Rename")))

    # --------------------------------------------------- all-to-all
    # pipelined exchanges (reference: planner/exchange/ fed by the
    # streaming executor): map-side tasks start as upstream blocks
    # materialize instead of after a materialize-all barrier
    def repartition(self, num_blocks: int, **kwargs) -> "Dataset":
        return Dataset(self._plan.with_op(ExchangeOp(
            lambda it, hint: shuffle_mod.streaming_repartition(
                it, num_blocks),
            name=f"Repartition({num_blocks})", out_count=num_blocks)))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None,
                       **kwargs) -> "Dataset":
        return Dataset(self._plan.with_op(ExchangeOp(
            lambda it, hint: shuffle_mod.streaming_random_shuffle(
                it, seed=seed, num_blocks=num_blocks, count_hint=hint),
            name="RandomShuffle")))

    def sort(self, key: str, descending: bool = False, **kwargs
             ) -> "Dataset":
        return Dataset(self._plan.with_op(ExchangeOp(
            lambda it, hint: shuffle_mod.streaming_sort(
                it, key, descending),
            name=f"Sort({key})")))

    def limit(self, n: int) -> "Dataset":
        return Dataset(self._plan.with_op(LimitOp(n)))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(self._plan.with_op(
            UnionOp([o._plan for o in others])))

    def zip(self, other: "Dataset") -> "Dataset":
        """Reference ``ZipOperator``: column-wise join by row position."""
        other_plan = other._plan

        def do_zip(refs: List[Any]) -> List[Any]:
            counts = ray_tpu.get(
                [shuffle_mod._r(shuffle_mod._rows).remote(r)
                 for r in refs])
            other_refs = shuffle_mod.repartition_to_counts(
                list(execute_streaming(other_plan)), counts)
            return [shuffle_mod._r(_zip_blocks).remote(a, b)
                    for a, b in zip(refs, other_refs)]
        return Dataset(self._plan.with_op(AllToAllOp(do_zip, name="Zip")))

    def groupby(self, key: str) -> "GroupedData":
        from ray_tpu.data.grouped_data import GroupedData
        return GroupedData(self, key)

    # --------------------------------------------------- consumption
    def iter_block_refs(self) -> Iterator[Any]:
        yield from execute_streaming(self._plan)

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self.iter_block_refs():
            yield ray_tpu.get(ref)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: Optional[str] = None,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     **_ignored) -> Iterator[Any]:
        from ray_tpu.data.iterator import iter_batches_over_blocks
        batch_format = batch_format or \
            DataContext.get_current().default_batch_format
        yield from iter_batches_over_blocks(
            self.iter_blocks(), batch_size, batch_format, drop_last,
            local_shuffle_buffer_size, local_shuffle_seed)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        kwargs["batch_format"] = "numpy"
        for batch in self.iter_batches(**kwargs):
            import torch
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def take(self, limit: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20,
                   batch_format: Optional[str] = None) -> Any:
        it = self.iter_batches(batch_size=batch_size,
                               batch_format=batch_format)
        return next(it)

    def count(self) -> int:
        refs = list(self.iter_block_refs())
        rows_fn = shuffle_mod._r(shuffle_mod._rows)
        return sum(ray_tpu.get([rows_fn.remote(r) for r in refs]))

    def schema(self) -> Optional[pa.Schema]:
        last = None
        for block in self.iter_blocks():
            if block.schema is not None and len(block.schema.names):
                if block.num_rows > 0:
                    return block.schema
                last = block.schema
        return last

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def num_blocks(self) -> int:
        n = self._plan.source_len()
        for op in self._plan.ops:
            if isinstance(op, ExchangeOp) and op.out_count is not None:
                n = op.out_count
        return n

    def size_bytes(self) -> int:
        return sum(b.nbytes for b in self.iter_blocks())

    # -- aggregates ---------------------------------------------------
    def _agg(self, col: str, np_fn) -> Any:
        vals = [np_fn(BlockAccessor(b).to_numpy([col])[col])
                for b in self.iter_blocks() if b.num_rows > 0]
        return np_fn(np.asarray(vals)) if vals else None

    def sum(self, col: str) -> Any:
        vals = [np.sum(BlockAccessor(b).to_numpy([col])[col])
                for b in self.iter_blocks() if b.num_rows > 0]
        return float(np.sum(vals)) if vals else None

    def min(self, col: str) -> Any:
        return self._agg(col, np.min)

    def max(self, col: str) -> Any:
        return self._agg(col, np.max)

    def mean(self, col: str) -> Any:
        total, n = 0.0, 0
        for b in self.iter_blocks():
            if b.num_rows:
                arr = BlockAccessor(b).to_numpy([col])[col]
                total += float(np.sum(arr))
                n += len(arr)
        return total / n if n else None

    def std(self, col: str) -> Any:
        arrs = [BlockAccessor(b).to_numpy([col])[col]
                for b in self.iter_blocks() if b.num_rows]
        if not arrs:
            return None
        rows = np.concatenate(arrs)
        return float(np.std(rows, ddof=1)) if len(rows) > 1 else 0.0

    def unique(self, col: str) -> List[Any]:
        seen = set()
        for b in self.iter_blocks():
            seen.update(BlockAccessor(b).to_numpy([col])[col].tolist())
        return sorted(seen)

    # -- materialization / split --------------------------------------
    def materialize(self) -> "MaterializedDataset":
        refs = list(self.iter_block_refs())
        return MaterializedDataset(
            ExecutionPlan(InputDataOp(refs)))

    def split(self, n: int, *, equal: bool = False,
              locality_hints=None) -> List["MaterializedDataset"]:
        refs = list(self.iter_block_refs())
        if equal:
            refs = shuffle_mod.repartition(
                refs, max(n, (len(refs) // n) * n) if len(refs) >= n
                else n)
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [MaterializedDataset(ExecutionPlan(InputDataOp(s)))
                for s in shards]

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> List["DataIterator"]:
        """Per-worker shard iterators (reference ``OutputSplitter`` /
        ``streaming_split``) — feeds Train workers."""
        from ray_tpu.data.iterator import make_streaming_shards
        return make_streaming_shards(self, n, equal=equal)

    def to_pandas(self):
        import pandas as pd
        blocks = list(self.iter_blocks())
        if not blocks:
            return pd.DataFrame()
        return BlockAccessor.concat(blocks).to_pandas()

    # -- writes -------------------------------------------------------
    def write_parquet(self, path: str,
                      partition_cols=None, **kwargs) -> None:
        from ray_tpu.data.datasource import write_blocks
        write_blocks(self, path, "parquet",
                     partition_cols=partition_cols)

    def write_csv(self, path: str, **kwargs) -> None:
        from ray_tpu.data.datasource import write_blocks
        write_blocks(self, path, "csv")

    def write_json(self, path: str, **kwargs) -> None:
        from ray_tpu.data.datasource import write_blocks
        write_blocks(self, path, "json")

    # -- misc ---------------------------------------------------------
    def stats(self) -> str:
        return repr(self._plan)

    def __repr__(self):
        return f"Dataset(plan={self._plan!r})"


class MaterializedDataset(Dataset):
    """Fully-executed dataset pinned in the object store
    (reference ``MaterializedDataset``)."""

    @property
    def block_refs(self) -> List[Any]:
        return self._plan.source.block_refs


def _zip_blocks(a: Block, b: Block) -> Block:
    cols = {name: a[name] for name in a.column_names}
    for name in b.column_names:
        out_name = name if name not in cols else f"{name}_1"
        cols[out_name] = b[name]
    return pa.table(cols)
