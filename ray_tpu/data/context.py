"""DataContext: execution knobs (reference:
``python/ray/data/context.py`` — ``DataContext.get_current()``)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # Streaming backpressure: max map tasks in flight per operator.
    max_tasks_in_flight_per_operator: int = 8
    # Default batch format for map_batches/iter_batches.
    default_batch_format: str = "numpy"
    # Parallelism used by read_*/range when not given.
    default_parallelism: int = 8
    use_push_based_shuffle: bool = False
    eager_free: bool = True

    _current: "Optional[DataContext]" = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current
