"""DataContext: execution knobs (reference:
``python/ray/data/context.py`` — ``DataContext.get_current()``)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # Streaming backpressure: the consumer-paced credit window. In the
    # generator-fed executor this maps onto the streaming layer's
    # ``generator_backpressure_num_objects`` (split across the stage's
    # generator members), so at most this many output blocks per stage
    # are in flight ahead of consumption; in the ``staged`` fallback it
    # is the per-operator in-order task window it always was.
    max_tasks_in_flight_per_operator: int = 8
    # Default batch format for map_batches/iter_batches.
    default_batch_format: str = "numpy"
    # Parallelism used by read_*/range when not given.
    default_parallelism: int = 8
    use_push_based_shuffle: bool = False
    eager_free: bool = True
    # ------------------------------------------------ streaming executor
    #: "streaming" (default): fused one-to-one stages run as long-lived
    #: generator tasks / actor-pool members consuming their upstream
    #: stream, so stage N+1 starts the moment stage N yields its first
    #: block. "staged": the serialized baseline — per-block tasks with
    #: an in-order submission window and a materialize barrier between
    #: stages (what `bench.py --data` measures streaming against).
    execution_mode: str = "streaming"
    #: yield blocks in submission order (deterministic — what `sort`/
    #: `limit`/`take` assume) instead of completion order. Disable for
    #: order-insensitive consumers (training shards): completion order
    #: is surfaced via ``wait_any`` so one straggler block never stalls
    #: the stream.
    preserve_order: bool = True
    #: generator members per fused task-compute stage (actor-pool stages
    #: use their pool size). None = min(#input blocks, in-flight window).
    streaming_stage_parallelism: Optional[int] = None
    #: `iter_batches` keeps this many resolved blocks ahead of the
    #: consume path (per shard) by default.
    prefetch_batches: int = 2
    #: depth of the pipelined row-count lookahead the equal-split
    #: coordinator keeps in flight (so balancing never stalls a shard).
    split_count_pipeline_depth: int = 4

    _current: "Optional[DataContext]" = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current
