"""Read/write APIs: range, from_items/pandas/numpy, parquet/csv/json/
text/tfrecords/binary files.

Reference: ``python/ray/data/read_api.py`` + ``datasource/`` (parquet,
csv, json, range, …). Each read resolves to N zero-arg read tasks (one
per file / range shard); the fused executor runs read+transforms as one
task per block.
"""

from __future__ import annotations

import builtins
import functools
import glob as glob_mod
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, _to_table
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, MaterializedDataset
from ray_tpu.data._internal.plan import ExecutionPlan, InputDataOp, ReadOp


def _make_dataset(tasks: List[Callable[[], Block]], name: str) -> Dataset:
    return Dataset(ExecutionPlan(ReadOp(tasks, name=name)))


def _resolve_paths(paths: Union[str, List[str]], suffixes) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = os.path.expanduser(p)
        if os.path.isdir(p):
            for suffix in suffixes:
                out.extend(sorted(glob_mod.glob(
                    os.path.join(p, f"**/*{suffix}"), recursive=True)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    out = [p for p in out if os.path.isfile(p)]
    if not out:
        raise FileNotFoundError(f"No files found for {paths!r}")
    return out


# ------------------------------------------------------------- sources
def range(n: int, *, parallelism: int = -1) -> Dataset:
    """Integers [0, n) in column "id" (reference ``ray.data.range``)."""
    ctx = DataContext.get_current()
    p = parallelism if parallelism > 0 else min(
        ctx.default_parallelism, max(1, n))
    base, extra = divmod(n, p)

    def make_task(start: int, count: int) -> Callable[[], Block]:
        return lambda: pa.table(
            {"id": np.arange(start, start + count, dtype=np.int64)})

    tasks, start = [], 0
    for i in builtins.range(p):
        count = base + (1 if i < extra else 0)
        tasks.append(make_task(start, count))
        start += count
    return _make_dataset(tasks, "Range")


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = -1) -> Dataset:
    ds = range(n, parallelism=parallelism)
    size = int(np.prod(shape))

    def to_tensor(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        ids = batch["id"]
        data = np.repeat(ids[:, None], size, axis=1).reshape(
            (len(ids),) + shape)
        return {"data": data}
    return ds.map_batches(to_tensor, batch_format="numpy")


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    p = parallelism if parallelism > 0 else min(
        ctx.default_parallelism, max(1, len(items)))
    chunks = np.array_split(np.arange(len(items)), p)

    def make_task(idx: np.ndarray) -> Callable[[], Block]:
        chunk = [items[i] for i in idx]
        def read() -> Block:
            if chunk and isinstance(chunk[0], dict):
                return pa.Table.from_pylist(chunk)
            return pa.table({"item": pa.array(chunk)})
        return read
    return _make_dataset([make_task(c) for c in chunks if len(c)],
                         "FromItems")


def from_pandas(dfs) -> MaterializedDataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    import ray_tpu
    refs = [ray_tpu.put(_to_table(df)) for df in dfs]
    return MaterializedDataset(ExecutionPlan(InputDataOp(refs)))


def from_numpy(arrays) -> MaterializedDataset:
    if not isinstance(arrays, list):
        arrays = [arrays]
    import ray_tpu
    refs = [ray_tpu.put(_to_table({"data": a})) for a in arrays]
    return MaterializedDataset(ExecutionPlan(InputDataOp(refs)))


def from_arrow(tables) -> MaterializedDataset:
    if not isinstance(tables, list):
        tables = [tables]
    import ray_tpu
    refs = [ray_tpu.put(t) for t in tables]
    return MaterializedDataset(ExecutionPlan(InputDataOp(refs)))


def from_huggingface(hf_dataset) -> Dataset:
    """Zero-copy-ish import of a HuggingFace datasets.Dataset (arrow)."""
    table = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if table is None:
        return from_items([dict(r) for r in hf_dataset])
    return from_arrow(table.combine_chunks())


def from_torch(torch_dataset) -> Dataset:
    return from_items([{"item": torch_dataset[i]}
                       for i in builtins.range(len(torch_dataset))])


# --------------------------------------------------------------- files
def _file_read_dataset(paths, suffixes, read_one: Callable[[str], Block],
                       name: str) -> Dataset:
    files = _resolve_paths(paths, suffixes)

    def make_task(path: str) -> Callable[[], Block]:
        return lambda: read_one(path)
    return _make_dataset([make_task(f) for f in files], name)


def _pack_files_by_size(files: List[str],
                        target_bytes: int,
                        size_of: Optional[Callable[[str], int]] = None
                        ) -> List[List[str]]:
    """Block-size targeting (reference: FileBasedDatasource's
    target-block-size file grouping): pack small files into one read
    task until ~target_bytes so a directory of tiny files doesn't
    become thousands of tiny blocks."""
    size_of = size_of or (lambda p: os.path.getsize(p))
    groups: List[List[str]] = []
    cur: List[str] = []
    cur_bytes = 0
    for f in files:
        s = max(1, size_of(f))
        if cur and cur_bytes + s > target_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(f)
        cur_bytes += s
    if cur:
        groups.append(cur)
    return groups


def _grouped_read_dataset(paths, suffixes,
                          read_group: Callable[[List[str]], Block],
                          name: str,
                          target_bytes: Optional[int] = None,
                          size_of=None) -> Dataset:
    ctx = DataContext.get_current()
    files = _resolve_paths(paths, suffixes)
    groups = _pack_files_by_size(
        files, target_bytes or ctx.target_max_block_size, size_of)

    def make_task(group: List[str]) -> Callable[[], Block]:
        return lambda: read_group(group)
    return _make_dataset([make_task(g) for g in groups], name)


_IMAGE_SUFFIXES = [".png", ".jpg", ".jpeg", ".gif", ".bmp", ".webp",
                   ".tif", ".tiff"]


def read_images(paths, *, size: Optional[tuple] = None,
                mode: Optional[str] = None,
                include_paths: bool = False, **kwargs) -> Dataset:
    """Image files into an ``image`` tensor column (reference:
    ``python/ray/data/datasource/image_datasource.py``). ``size=(h, w)``
    resizes (and is required when source images vary in shape);
    ``mode`` converts (e.g. "RGB", "L"). Files are packed into blocks
    targeting the context block size based on DECODED bytes."""
    from PIL import Image

    def decoded_size(p: str) -> int:
        if size is not None:
            channels = 1 if mode == "L" else 3
            return size[0] * size[1] * channels
        # compressed-on-disk size underestimates decoded; ~10x is a
        # serviceable planning figure for typical jpeg/png
        return os.path.getsize(p) * 10

    def read_group(group: List[str]) -> Block:
        arrays, used_paths = [], []
        for p in group:
            img = Image.open(p)
            if mode is not None:
                img = img.convert(mode)
            if size is not None:
                img = img.resize((size[1], size[0]))
            arrays.append(np.asarray(img))
            used_paths.append(p)
        shapes = {a.shape for a in arrays}
        if len(shapes) > 1:
            raise ValueError(
                f"images have differing shapes {sorted(shapes)}; pass "
                f"size=(h, w) to read_images to resize them")
        cols: Dict[str, Any] = {"image": np.stack(arrays)}
        table = _to_table(cols)
        if include_paths:
            table = table.append_column("path", pa.array(used_paths))
        return table

    return _grouped_read_dataset(paths, _IMAGE_SUFFIXES, read_group,
                                 "ReadImages", size_of=decoded_size)


def read_parquet(paths, *, split_row_groups: bool = True,
                 **kwargs) -> Dataset:
    """Parquet with driver-side metadata prefetch (reference:
    ``datasource/parquet_datasource.py:153`` prefetches file metadata to
    plan fragments): large files split into one read task per batch of
    row groups, so a few big files still parallelize; ``columns=`` /
    ``filters=`` push down into the arrow reader."""
    import pyarrow.parquet as pq
    files = _resolve_paths(paths, [".parquet"])
    target = DataContext.get_current().target_max_block_size
    tasks: List[Callable[[], Block]] = []
    for p in files:
        groups: List[List[int]] = []
        # row-group reads honor only columns=; any other reader kwarg
        # (filters, schema, memory_map, ...) forces the whole-file path
        # so its semantics apply uniformly regardless of file size
        if split_row_groups and not (set(kwargs) - {"columns"}):
            try:
                md = pq.ParquetFile(p).metadata  # footer only
                cur: List[int] = []
                cur_bytes = 0
                for g in builtins.range(md.num_row_groups):
                    sz = md.row_group(g).total_byte_size
                    if cur and cur_bytes + sz > target:
                        groups.append(cur)
                        cur, cur_bytes = [], 0
                    cur.append(g)
                    cur_bytes += sz
                if cur:
                    groups.append(cur)
            except Exception:
                groups = []
        if len(groups) > 1:
            for idx in groups:
                tasks.append(functools.partial(
                    lambda p, idx: pq.ParquetFile(p).read_row_groups(
                        idx, columns=kwargs.get("columns")), p, idx))
        else:
            tasks.append(functools.partial(
                lambda p: pq.read_table(p, **kwargs), p))
    return _make_dataset(tasks, "ReadParquet")


def read_csv(paths, **kwargs) -> Dataset:
    from pyarrow import csv as pacsv
    return _file_read_dataset(
        paths, [".csv"], lambda p: pacsv.read_csv(p, **kwargs), "ReadCSV")


def read_json(paths, **kwargs) -> Dataset:
    from pyarrow import json as pajson
    return _file_read_dataset(
        paths, [".json", ".jsonl"],
        lambda p: pajson.read_json(p, **kwargs), "ReadJSON")


def read_text(paths, **kwargs) -> Dataset:
    def read_group(group: List[str]) -> Block:
        lines: List[str] = []
        for p in group:
            with open(p, "r", errors="replace") as f:
                lines.extend(ln.rstrip("\n") for ln in f)
        return pa.table({"text": pa.array(lines)})
    return _grouped_read_dataset(paths, [".txt"], read_group, "ReadText")


def read_binary_files(paths, *, include_paths: bool = False,
                      **kwargs) -> Dataset:
    def read_group(group: List[str]) -> Block:
        blobs, names = [], []
        for p in group:
            with open(p, "rb") as f:
                blobs.append(f.read())
            names.append(p)
        cols: Dict[str, Any] = {"bytes": pa.array(blobs)}
        if include_paths:
            cols["path"] = pa.array(names)
        return pa.table(cols)
    return _grouped_read_dataset(paths, [""], read_group, "ReadBinary")


def read_numpy(paths, **kwargs) -> Dataset:
    def read_one(p: str) -> Block:
        return _to_table({"data": np.load(p)})
    return _file_read_dataset(paths, [".npy"], read_one, "ReadNumpy")


def read_tfrecords(paths, **kwargs) -> Dataset:
    """TFRecord files of ``tf.train.Example`` protos, WITHOUT tensorflow
    (reference: ``data/datasource/tfrecords_datasource.py`` uses the TF
    reader; here the record framing and the Example proto are decoded by
    hand — the formats are small and stable). Columns become Arrow
    arrays; singleton lists unwrap to scalars like the reference."""
    from ray_tpu.data._internal import tfrecords as tfr

    def read_one(p: str) -> Block:
        rows = [tfr.parse_example(rec) for rec in tfr.read_records(p)]
        if not rows:
            return pa.table({})
        keys = sorted({k for r in rows for k in r})
        cols: Dict[str, list] = {}
        for k in keys:
            vals = [r.get(k) for r in rows]
            # singleton unwrap can mix scalars and lists across records;
            # arrow needs one shape — promote everything to lists if any
            # record carried more than one value
            if any(isinstance(v, list) for v in vals):
                vals = [v if isinstance(v, list)
                        else ([] if v is None else [v]) for v in vals]
            cols[k] = vals
        return pa.table(cols)

    return _file_read_dataset(paths, [".tfrecord", ".tfrecords"],
                              read_one, "ReadTFRecords")


def read_webdataset(paths, **kwargs) -> Dataset:
    """WebDataset tar shards (reference:
    ``data/datasource/webdataset_datasource.py``): files grouped by key
    (basename before the first dot); each group becomes one row with a
    column per extension plus ``__key__``."""
    import tarfile

    def read_one(p: str) -> Block:
        groups: Dict[str, Dict[str, bytes]] = {}
        order: List[str] = []
        with tarfile.open(p) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                # key keeps the directory prefix (webdataset semantics:
                # train/0001.jpg and val/0001.jpg are distinct samples)
                name = member.name
                base = os.path.basename(name)
                if "." not in base:
                    key, ext = name, "bin"
                else:
                    ext = base.split(".", 1)[1]
                    key = name[: len(name) - len(ext) - 1]
                if key not in groups:
                    groups[key] = {}
                    order.append(key)
                groups[key][ext] = tf.extractfile(member).read()
        exts = sorted({e for g in groups.values() for e in g})
        import pyarrow as pa
        cols = {"__key__": order}
        for e in exts:
            cols[e] = [groups[k].get(e) for k in order]
        return pa.table(cols)

    return _file_read_dataset(paths, [".tar"], read_one,
                              "ReadWebDataset")


def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             parallelism: int = 1) -> Dataset:
    """Rows of a SQL query over any DB-API 2.0 connection (reference:
    ``data/datasource/sql_datasource.py`` — connection factory + query;
    shards parallelize via LIMIT/OFFSET exactly like the reference's
    ``_read_stream`` pagination). ``connection_factory`` must be
    picklable (e.g. ``functools.partial(sqlite3.connect, path)``)."""

    def fetch(query: str) -> Block:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(query)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return pa.table({c: [r[i] for r in rows]
                         for i, c in enumerate(cols)})

    if parallelism <= 1:
        return _make_dataset([functools.partial(fetch, sql)], "ReadSQL")
    if "order by" not in sql.lower():
        # LIMIT/OFFSET shards over an unordered query have no stable
        # row assignment: concurrent shards could duplicate/miss rows
        raise ValueError(
            "read_sql with parallelism > 1 requires an ORDER BY in the "
            "query (LIMIT/OFFSET sharding needs a deterministic order)")
    # shard by LIMIT/OFFSET over a deterministic total count (the
    # derived table needs an alias on PostgreSQL/MySQL)
    conn = connection_factory()
    try:
        cur = conn.cursor()
        cur.execute(f"SELECT COUNT(*) FROM ({sql}) AS __rt_count")
        total = int(cur.fetchone()[0])
    finally:
        conn.close()
    per = max(1, -(-total // parallelism))
    tasks = [functools.partial(
        fetch, f"{sql} LIMIT {per} OFFSET {off}")
        for off in builtins.range(0, max(total, 1), per)]
    return _make_dataset(tasks, "ReadSQL")


def read_bigquery(project_id: str, *, query: Optional[str] = None,
                  dataset: Optional[str] = None,
                  client_factory: Optional[Callable[[], Any]] = None
                  ) -> Dataset:
    """BigQuery rows (reference: ``datasource/bigquery_datasource.py``
    over ``google.cloud.bigquery``). The client library is not in the
    hermetic TPU image, so a ``client_factory`` is injectable; without
    one, ``google.cloud.bigquery.Client`` is imported lazily."""
    if query is None and dataset is None:
        raise ValueError("read_bigquery needs query= or dataset= "
                         "('dataset.table')")

    def fetch() -> Block:
        if client_factory is not None:
            client = client_factory()
        else:
            try:
                from google.cloud import bigquery
            except ImportError as e:
                raise ImportError(
                    "google-cloud-bigquery is not installed in this "
                    "image; pass client_factory= to inject a client"
                ) from e
            client = bigquery.Client(project=project_id)
        if query is not None:
            result = client.query(query).result()
        else:
            ds_id, table_id = dataset.split(".", 1)
            result = client.list_rows(f"{project_id}.{ds_id}.{table_id}")
        arrow = result.to_arrow()
        return arrow

    return _make_dataset([fetch], "ReadBigQuery")


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[dict]] = None,
               client_factory: Optional[Callable[[], Any]] = None
               ) -> Dataset:
    """MongoDB documents (reference: ``datasource/mongo_datasource.py``
    over pymongo/pymongoarrow). pymongo is not in the hermetic image, so
    a ``client_factory`` is injectable. Documents become one row each;
    ``_id`` is stringified."""

    def fetch() -> Block:
        if client_factory is not None:
            client = client_factory()
        else:
            try:
                import pymongo
            except ImportError as e:
                raise ImportError(
                    "pymongo is not installed in this image; pass "
                    "client_factory= to inject a client") from e
            client = pymongo.MongoClient(uri)
        coll = client[database][collection]
        docs = list(coll.aggregate(pipeline) if pipeline
                    else coll.find())
        if not docs:
            return pa.table({})
        keys = sorted({k for d in docs for k in d})
        cols = {}
        for k in keys:
            vals = [d.get(k) for d in docs]
            if k == "_id":
                vals = [str(v) for v in vals]
            cols[k] = vals
        return pa.table(cols)

    return _make_dataset([fetch], "ReadMongo")


# --------------------------------------------------------------- write
def write_blocks(ds: Dataset, path: str, fmt: str,
                 partition_cols: Optional[List[str]] = None) -> None:
    os.makedirs(path, exist_ok=True)

    def write_one(block, out: str) -> None:
        if fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(block, out)
        elif fmt == "csv":
            from pyarrow import csv as pacsv
            pacsv.write_csv(block, out)
        elif fmt == "json":
            block.to_pandas().to_json(out, orient="records", lines=True)
        else:
            raise ValueError(fmt)

    for i, block in enumerate(ds.iter_blocks()):
        if block.num_rows == 0:
            continue
        if partition_cols:
            # hive-style partitioned layout (reference:
            # ``datasource/parquet_datasource.py`` partitioned writes:
            # path/key=value/.../part-*.ext, partition columns dropped
            # from the file payload). dropna=False + the hive null
            # bucket: pandas' default dropna would SILENTLY drop every
            # row whose partition value is null.
            df = block.to_pandas()
            for c in partition_cols:
                df[c] = df[c].fillna("__HIVE_DEFAULT_PARTITION__")
            for j, (key, part) in enumerate(
                    df.groupby(partition_cols, sort=True,
                               dropna=False)):
                if not isinstance(key, tuple):
                    key = (key,)
                sub = os.path.join(path, *(
                    f"{c}={v}" for c, v in zip(partition_cols, key)))
                os.makedirs(sub, exist_ok=True)
                payload = pa.Table.from_pandas(
                    part.drop(columns=list(partition_cols)),
                    preserve_index=False)
                write_one(payload, os.path.join(
                    sub, f"part-{i:05d}-{j:03d}.{fmt}"))
            continue
        write_one(block, os.path.join(path, f"part-{i:05d}.{fmt}"))
