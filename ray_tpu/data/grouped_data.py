"""GroupBy + aggregations.

Reference: ``python/ray/data/grouped_data.py`` — hash/sort-partition the
dataset by key, then aggregate per group (count/sum/min/max/mean/std,
``map_groups``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockAccessor, _to_table


class GroupedData:
    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def _grouped_tables(self):
        """Sort by key, then split contiguous key runs (one pass)."""
        ds = self._ds.sort(self._key)
        merged = BlockAccessor.concat(list(ds.iter_blocks()))
        if merged.num_rows == 0:
            return []
        keys = merged[self._key].to_numpy(zero_copy_only=False)
        bounds = [0] + (np.nonzero(keys[1:] != keys[:-1])[0] + 1).tolist() \
            + [len(keys)]
        return [(keys[bounds[i]], merged.slice(
            bounds[i], bounds[i + 1] - bounds[i]))
            for i in range(len(bounds) - 1)]

    def _agg(self, np_fn, cols: List[str], suffix: str):
        from ray_tpu.data.datasource import from_arrow
        rows = []
        for key_val, table in self._grouped_tables():
            row: Dict[str, Any] = {self._key: key_val}
            use = cols or [c for c in table.column_names
                           if c != self._key]
            for c in use:
                arr = table[c].to_numpy(zero_copy_only=False)
                row[f"{c}{suffix}"] = np_fn(arr)
            rows.append(row)
        return from_arrow(pa.Table.from_pylist(rows))

    def count(self):
        from ray_tpu.data.datasource import from_arrow
        rows = [{self._key: k, "count()": t.num_rows}
                for k, t in self._grouped_tables()]
        return from_arrow(pa.Table.from_pylist(rows))

    def sum(self, on=None):
        return self._agg(np.sum, self._cols(on), "_sum")

    def min(self, on=None):
        return self._agg(np.min, self._cols(on), "_min")

    def max(self, on=None):
        return self._agg(np.max, self._cols(on), "_max")

    def mean(self, on=None):
        return self._agg(np.mean, self._cols(on), "_mean")

    def std(self, on=None):
        return self._agg(lambda a: np.std(a, ddof=1) if len(a) > 1 else 0.0,
                         self._cols(on), "_std")

    def _cols(self, on) -> List[str]:
        if on is None:
            return []
        return [on] if isinstance(on, str) else list(on)

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"):
        from ray_tpu.data.datasource import from_arrow
        outs = []
        for _, table in self._grouped_tables():
            batch = BlockAccessor(table).to_batch(batch_format)
            outs.append(_to_table(fn(batch)))
        if not outs:
            return from_arrow(pa.table({}))
        return from_arrow(BlockAccessor.concat(outs))
