"""ray_tpu.data: streaming distributed datasets
(reference: ``python/ray/data/``).

Public surface mirrors ``ray.data``: ``range``/``from_*``/``read_*``
constructors, the lazy ``Dataset`` with fused streaming execution, and
``DataContext``.
"""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import (
    ActorPoolStrategy, Dataset, MaterializedDataset)
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.datasource import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    from_torch,
    range,
    range_tensor,
    read_binary_files,
    read_bigquery,
    read_images,
    read_csv,
    read_json,
    read_mongo,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "ActorPoolStrategy",
    "Block",
    "BlockAccessor",
    "DataContext",
    "DataIterator",
    "Dataset",
    "MaterializedDataset",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "from_torch",
    "range",
    "range_tensor",
    "read_bigquery",
    "read_binary_files",
    "read_images",
    "read_csv",
    "read_json",
    "read_mongo",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
