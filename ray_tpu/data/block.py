"""Blocks: the unit of data movement — Arrow tables in the object store.

Reference: ``python/ray/data/block.py`` (+
``_internal/arrow_block.py``) — a Dataset is a list of object-store
references to blocks; each block is a ``pyarrow.Table``. BlockAccessor
converts between Arrow, pandas, numpy-dict and row-dict views. The numpy
view is the TPU hand-off: contiguous host arrays ready for
``jax.device_put`` without an extra copy.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
# What user callables may return from map_batches: arrow, pandas,
# dict-of-numpy, or list of row dicts.
DataBatch = Union["pa.Table", "Dict[str, np.ndarray]", "Any"]


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema]

    @staticmethod
    def of(block: Block) -> "BlockMetadata":
        return BlockMetadata(block.num_rows, block.nbytes, block.schema)


def _to_table(data: DataBatch) -> pa.Table:
    """Normalize any supported batch format into an Arrow table."""
    if isinstance(data, pa.Table):
        return data
    if data is None:
        return pa.table({})
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    if isinstance(data, dict):
        import json
        arrays, fields = [], []
        for k, v in data.items():
            arr = np.asarray(v)
            if arr.ndim > 1:
                # Tensor columns: fixed-size lists + shape metadata so
                # to_numpy() reconstructs (n, *shape) contiguously
                # (minimal analog of the reference's ArrowTensorArray).
                flat = np.ascontiguousarray(
                    arr.reshape(arr.shape[0], -1))
                col = pa.FixedSizeListArray.from_arrays(
                    pa.array(flat.ravel()), flat.shape[1])
                field = pa.field(
                    k, col.type,
                    metadata={b"tensor_shape": json.dumps(
                        list(arr.shape[1:])).encode()})
            else:
                col = pa.array(arr)
                field = pa.field(k, col.type)
            arrays.append(col)
            fields.append(field)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    if isinstance(data, list):
        if not data:
            return pa.table({})
        if isinstance(data[0], dict):
            return pa.Table.from_pylist(data)
        return pa.table({"item": pa.array(data)})
    raise TypeError(f"Unsupported batch type: {type(data)}")


class BlockAccessor:
    """View/convert one block (reference ``BlockAccessor``)."""

    def __init__(self, block: Block):
        self._table = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- views --------------------------------------------------------
    def to_arrow(self) -> pa.Table:
        return self._table

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None
                 ) -> Dict[str, np.ndarray]:
        import json
        cols = columns or self._table.column_names
        out = {}
        for name in cols:
            col = self._table[name]
            field = self._table.schema.field(name)
            if pa.types.is_fixed_size_list(field.type):
                chunk = col.combine_chunks()
                flat = chunk.flatten().to_numpy(zero_copy_only=False)
                shape: List[int] = [len(chunk), field.type.list_size]
                meta = field.metadata or {}
                if b"tensor_shape" in meta:
                    shape = [len(chunk)] + json.loads(
                        meta[b"tensor_shape"].decode())
                out[name] = flat.reshape(shape)
                continue
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                out[name] = np.asarray(col.to_pylist())
        return out

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "default"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self._table
        raise ValueError(f"Unknown batch_format: {batch_format}")

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for row in self._table.to_pylist():
            yield row

    # -- ops ----------------------------------------------------------
    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> Optional[pa.Schema]:
        return self._table.schema

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take(self, indices) -> Block:
        return self._table.take(pa.array(indices))

    def select(self, columns: List[str]) -> Block:
        return self._table.select(columns)

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        tables = [b for b in blocks if b.num_rows > 0]
        if not tables:
            return blocks[0] if blocks else pa.table({})
        return pa.concat_tables(tables, promote_options="default")

    @staticmethod
    def builder() -> "BlockBuilder":
        return BlockBuilder()


class BlockBuilder:
    def __init__(self):
        self._rows: List[Dict[str, Any]] = []
        self._tables: List[pa.Table] = []

    def add(self, row: Dict[str, Any]) -> None:
        self._rows.append(row)

    def add_block(self, block: Block) -> None:
        self._flush_rows()
        self._tables.append(block)

    def _flush_rows(self) -> None:
        if self._rows:
            self._tables.append(pa.Table.from_pylist(self._rows))
            self._rows = []

    def num_rows(self) -> int:
        return sum(t.num_rows for t in self._tables) + len(self._rows)

    def build(self) -> Block:
        self._flush_rows()
        if not self._tables:
            return pa.table({})
        return BlockAccessor.concat(self._tables)
