"""TFRecord framing + tf.train.Example codec, dependency-free.

Reference: ``python/ray/data/datasource/tfrecords_datasource.py`` reads
TFRecords through tensorflow; the hermetic TPU image doesn't bake TF,
and the two formats involved are tiny and frozen, so they are decoded
by hand:

- TFRecord framing (tensorflow/core/lib/io/record_writer.cc):
  ``u64 length | u32 masked-crc32c(length) | bytes | u32 masked-crc(data)``
- ``tf.train.Example`` protobuf: Example{1: Features{1: map<string,
  Feature>}} with Feature = one of bytes_list(1)/float_list(2)/
  int64_list(3), each a repeated field.

CRCs are verified on read (crc32c via the polynomial table below);
write produces files tensorflow can read back.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Union

# ----------------------------------------------------------- crc32c
_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ----------------------------------------------------------- framing
def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,), (lcrc,) = (struct.unpack("<Q", header[:8]),
                                  struct.unpack("<I", header[8:]))
            if _masked_crc(header[:8]) != lcrc:
                raise ValueError(f"corrupt length crc in {path}")
            data = f.read(length)
            (dcrc,) = struct.unpack("<I", f.read(4))
            if _masked_crc(data) != dcrc:
                raise ValueError(f"corrupt data crc in {path}")
            yield data


def write_records(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            hdr = struct.pack("<Q", len(rec))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))


# ------------------------------------------------- minimal protobuf
def _read_varint(buf: bytes, i: int):
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fields(buf: bytes) -> Iterator[tuple]:
    """(field_number, wire_type, value) over a serialized message."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:      # varint
            val, i = _read_varint(buf, i)
        elif wire == 1:    # 64-bit
            val, i = buf[i:i + 8], i + 8
        elif wire == 2:    # length-delimited
            ln, i = _read_varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wire == 5:    # 32-bit
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_feature(buf: bytes):
    for field, _, val in _fields(buf):
        if field == 1:     # BytesList{1: repeated bytes}
            return [v for f, _, v in _fields(val) if f == 1]
        if field == 2:     # FloatList{1: repeated float, packed}
            floats: List[float] = []
            for f, wire, v in _fields(val):
                if f != 1:
                    continue
                if wire == 2:  # packed
                    floats.extend(struct.unpack(f"<{len(v)//4}f", v))
                else:
                    floats.append(struct.unpack("<f", v)[0])
            return floats
        if field == 3:     # Int64List{1: repeated int64, packed}
            ints: List[int] = []
            for f, wire, v in _fields(val):
                if f != 1:
                    continue
                if wire == 2:
                    i = 0
                    while i < len(v):
                        n, i = _read_varint(v, i)
                        ints.append(_to_signed(n))
                else:
                    ints.append(_to_signed(v))
            return ints
    return []


def _to_signed(n: int) -> int:
    return n - (1 << 64) if n >= (1 << 63) else n


def parse_example(record: bytes) -> Dict[str, Any]:
    """Example proto -> {name: scalar-or-list} (singletons unwrap)."""
    out: Dict[str, Any] = {}
    for field, _, features in _fields(record):
        if field != 1:   # Example.features
            continue
        for f2, _, entry in _fields(features):
            if f2 != 1:  # Features.feature map entries
                continue
            name, value = None, []
            for f3, _, v in _fields(entry):
                if f3 == 1:
                    name = v.decode()
                elif f3 == 2:
                    value = _parse_feature(v)
            if name is not None:
                out[name] = value[0] if len(value) == 1 else value
    return out


# ----------------------------------------------------------- encoding
def _encode_field(field: int, wire: int, payload: bytes) -> bytes:
    return _write_varint((field << 3) | wire) + payload


def _encode_len(field: int, payload: bytes) -> bytes:
    return _encode_field(field, 2, _write_varint(len(payload)) + payload)


def encode_example(row: Dict[str, Union[bytes, str, int, float, list]]
                   ) -> bytes:
    """{name: value} -> serialized tf.train.Example."""
    entries = b""
    for name, value in row.items():
        vals = value if isinstance(value, list) else [value]
        if all(isinstance(v, (bytes, str)) for v in vals):
            items = b"".join(
                _encode_len(1, v.encode() if isinstance(v, str) else v)
                for v in vals)
            feature = _encode_len(1, items)           # BytesList
        elif all(isinstance(v, int) for v in vals):
            packed = b"".join(_write_varint(v & ((1 << 64) - 1))
                              for v in vals)
            feature = _encode_len(3, _encode_len(1, packed))  # Int64List
        else:
            packed = struct.pack(f"<{len(vals)}f",
                                 *[float(v) for v in vals])
            feature = _encode_len(2, _encode_len(1, packed))  # FloatList
        entry = _encode_len(1, name.encode()) + _encode_len(2, feature)
        entries += _encode_len(1, entry)
    return _encode_len(1, entries)   # Example.features
