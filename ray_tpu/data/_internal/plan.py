"""Logical plan + fused streaming execution.

Reference: ``python/ray/data/_internal/plan.py`` (ExecutionPlan),
``logical/`` operators, and ``execution/streaming_executor.py:55``. The
design keeps the reference's two key properties, re-expressed compactly:

- **operator fusion**: consecutive one-to-one ops (read→map→filter…)
  fuse into a single remote task per block (reference
  ``logical/rules/operator_fusion.py``), so a ``read_parquet →
  map_batches → filter`` chain costs one task per block, not three.
- **streaming with backpressure**: blocks flow through the fused stages
  as a pull-based iterator with a bounded number of in-flight tasks
  (reference ``StreamingExecutor._scheduling_loop_step`` +
  backpressure policies); downstream consumption paces submission.

All-to-all ops (shuffle/sort/repartition) are barriers, as in the
reference's exchange operators (``planner/exchange/``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, _to_table
from ray_tpu.data.context import DataContext


# ---------------------------------------------------------------- ops
@dataclass
class ReadOp:
    """Source: a list of zero-arg callables each producing a Block."""
    tasks: List[Callable[[], Block]]
    name: str = "Read"


@dataclass
class InputDataOp:
    """Source: pre-materialized block refs."""
    block_refs: List[Any]
    name: str = "InputData"


@dataclass
class OneToOneOp:
    """A per-block transform: fn(Block) -> Block. Fusable."""
    fn: Callable[[Block], Block]
    name: str = "Map"
    # actor-pool compute (None = task pool)
    actor_pool_size: Optional[int] = None
    fn_constructor: Optional[Callable[[], Any]] = None
    num_cpus: Optional[float] = None


@dataclass
class AllToAllOp:
    """Barrier op over the full materialized block list."""
    fn: Callable[[List[Any]], List[Any]]  # refs -> refs
    name: str = "AllToAll"


@dataclass
class ExchangeOp:
    """Pipelined all-to-all (reference: planner/exchange/ operators fed
    by the streaming executor): ``run`` receives the upstream ref
    ITERATOR so map-side tasks launch as blocks materialize; only the
    reduce phase barriers. ``count_hint`` is the statically-known
    upstream block count (None after limit/union)."""
    run: Callable[..., List[Any]]  # (ref_iter, count_hint) -> refs
    name: str = "Exchange"
    #: statically-known output block count (repartition(n)); None keeps
    #: the upstream count (shuffle/sort)
    out_count: Optional[int] = None


@dataclass
class LimitOp:
    n: int
    name: str = "Limit"


@dataclass
class UnionOp:
    others: List["ExecutionPlan"]
    name: str = "Union"


class ExecutionPlan:
    def __init__(self, source, ops: Optional[List[Any]] = None):
        self.source = source  # ReadOp | InputDataOp
        self.ops: List[Any] = ops or []

    def with_op(self, op) -> "ExecutionPlan":
        return ExecutionPlan(self.source, self.ops + [op])

    def source_len(self) -> int:
        if isinstance(self.source, ReadOp):
            return len(self.source.tasks)
        return len(self.source.block_refs)

    def __repr__(self):
        names = [getattr(self.source, "name", "?")] + [
            op.name for op in self.ops]
        return " -> ".join(names)


# ----------------------------------------------------------- execution
def _apply_chain(fns: List[Callable[[Block], Block]], item) -> Block:
    """The fused stage body: run a producer or block through the chain
    of one-to-one transforms. Runs remotely, one task per block."""
    block = item() if callable(item) else item
    for fn in fns:
        block = fn(block)
    return block


class _ActorStage:
    """Actor holding stateful transform constructors for an actor-pool
    stage (reference ``ActorPoolMapOperator``; callable-class UDFs)."""

    def __init__(self, constructors: List[Optional[Callable]]):
        self._instances = [c() if c is not None else None
                           for c in constructors]

    def apply(self, fns: List[Callable], item) -> Block:
        block = item() if callable(item) else item
        for fn, inst in zip(fns, self._instances):
            if inst is not None:
                block = fn(block, inst)
            else:
                block = fn(block)
        return block


def _fuse(ops: List[Any]) -> List[Any]:
    """Group consecutive OneToOneOps with compatible compute into fused
    stages; barrier/limit ops pass through."""
    fused: List[Any] = []
    buf: List[OneToOneOp] = []

    def flush():
        if buf:
            fused.append(list(buf))
            buf.clear()

    prev_pool: Optional[int] = None
    for op in ops:
        if isinstance(op, OneToOneOp):
            if buf and op.actor_pool_size != prev_pool:
                flush()
            buf.append(op)
            prev_pool = op.actor_pool_size
        else:
            flush()
            fused.append(op)
    flush()
    return fused


def execute_streaming(plan: ExecutionPlan,
                      ctx: Optional[DataContext] = None
                      ) -> Iterator[Any]:
    """Yield output block refs, submitting at most
    ``ctx.max_tasks_in_flight_per_operator`` tasks ahead of consumption."""
    ctx = ctx or DataContext.get_current()

    # Source items: callables (read tasks) or ready refs.
    if isinstance(plan.source, ReadOp):
        items: Iterator[Any] = iter(plan.source.tasks)
        items_are_refs = False
    else:
        items = iter(plan.source.block_refs)
        items_are_refs = True

    stages = _fuse(plan.ops)
    stream = _run_stages(items, items_are_refs, stages, ctx,
                         plan.source_len())
    yield from stream


def _run_stages(items: Iterator[Any], items_are_refs: bool,
                stages: List[Any], ctx: DataContext,
                count_hint: Optional[int] = None) -> Iterator[Any]:
    if not stages:
        # Source only: materialize reads into refs.
        if items_are_refs:
            yield from items
        else:
            yield from _window_map(
                items, lambda task: _remote_apply([], task), ctx)
        return

    stage, rest = stages[0], stages[1:]
    if isinstance(stage, list):  # fused one-to-one stage
        out = _run_fused_stage(items, items_are_refs, stage, ctx)
        yield from _run_stages(out, True, rest, ctx, count_hint)
    elif isinstance(stage, ExchangeOp):
        upstream = _run_stages(items, items_are_refs, [], ctx,
                               count_hint)
        out_refs = stage.run(upstream, count_hint)
        yield from _run_stages(iter(out_refs), True, rest, ctx,
                               len(out_refs))
    elif isinstance(stage, AllToAllOp):
        refs = list(_run_stages(items, items_are_refs, [], ctx,
                                count_hint))
        out_refs = stage.fn(refs)
        yield from _run_stages(iter(out_refs), True, rest, ctx,
                               len(out_refs))
    elif isinstance(stage, LimitOp):
        out = _run_limit(
            _run_stages(items, items_are_refs, [], ctx, count_hint),
            stage.n)
        # limit truncates an unknown number of blocks: no hint below
        yield from _run_stages(out, True, rest, ctx, None)
    elif isinstance(stage, UnionOp):
        def chained():
            yield from _run_stages(items, items_are_refs, [], ctx,
                                   count_hint)
            for other in stage.others:
                yield from execute_streaming(other, ctx)
        # other branches' output counts aren't statically derived here
        yield from _run_stages(chained(), True, rest, ctx, None)
    else:
        raise TypeError(f"Unknown stage: {stage!r}")


_remote_apply_cached: Dict[float, Any] = {}


def _get_remote_apply(num_cpus: float = 1.0):
    if num_cpus not in _remote_apply_cached:
        _remote_apply_cached[num_cpus] = ray_tpu.remote(
            num_cpus=num_cpus)(_apply_chain)
    return _remote_apply_cached[num_cpus]


def _remote_apply(fns, item, num_cpus: float = 1.0):
    return _get_remote_apply(num_cpus).remote(fns, item)


def _window_map(items: Iterator[Any], submit: Callable[[Any], Any],
                ctx: DataContext) -> Iterator[Any]:
    """Submit tasks keeping a bounded in-flight window; yield refs in
    order (ordered streaming, like the reference's default)."""
    window = ctx.max_tasks_in_flight_per_operator
    inflight: List[Any] = []
    for item in items:
        inflight.append(submit(item))
        if len(inflight) >= window:
            yield inflight.pop(0)
    while inflight:
        yield inflight.pop(0)


def _run_fused_stage(items: Iterator[Any], items_are_refs: bool,
                     stage: List[OneToOneOp], ctx: DataContext
                     ) -> Iterator[Any]:
    pool_size = stage[0].actor_pool_size
    stage_cpus = max((op.num_cpus or 1.0) for op in stage)
    if pool_size is None:
        fns = [op.fn for op in stage]
        yield from _window_map(
            items, lambda item: _remote_apply(fns, item, stage_cpus), ctx)
        return
    # Actor-pool stage: round-robin blocks over a pool of stage actors.
    constructors = [op.fn_constructor for op in stage]
    fns = [op.fn for op in stage]
    actor_cls = ray_tpu.remote(num_cpus=stage_cpus)(_ActorStage)
    actors = [actor_cls.remote(constructors) for _ in range(pool_size)]
    submitted: List[Any] = []
    try:
        i = 0
        window = max(pool_size * 2, ctx.max_tasks_in_flight_per_operator)
        inflight: List[Any] = []
        for item in items:
            actor = actors[i % pool_size]
            i += 1
            ref = actor.apply.remote(fns, item)
            submitted.append(ref)
            inflight.append(ref)
            if len(inflight) >= window:
                yield inflight.pop(0)
        while inflight:
            yield inflight.pop(0)
    finally:
        # Yielded refs may not have been consumed yet — wait for every
        # submitted task to finish (results outlive the actors in the
        # object store) BEFORE tearing the pool down.
        if submitted:
            try:
                ray_tpu.wait(submitted, num_returns=len(submitted),
                             timeout=600)
            except Exception:
                pass
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _slice_block(block: Block, n: int) -> Block:
    return BlockAccessor(block).slice(0, n)


def _run_limit(refs: Iterator[Any], n: int) -> Iterator[Any]:
    from ray_tpu.data._internal import shuffle as sh
    remaining = n
    rows_fn = sh._r(sh._rows)
    slice_fn = sh._r(_slice_block)
    for ref in refs:
        if remaining <= 0:
            break
        rows = ray_tpu.get(rows_fn.remote(ref))
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield slice_fn.remote(ref, remaining)
            remaining = 0
