"""Logical plan + fused streaming execution.

Reference: ``python/ray/data/_internal/plan.py`` (ExecutionPlan),
``logical/`` operators, and ``execution/streaming_executor.py:55``. The
design keeps the reference's two key properties, re-expressed compactly:

- **operator fusion**: consecutive one-to-one ops (read→map→filter…)
  fuse into a single stage per block (reference
  ``logical/rules/operator_fusion.py``), so a ``read_parquet →
  map_batches → filter`` chain costs one hop per block, not three.
- **generator-fed streaming**: each fused stage is a small pool of
  long-lived ``num_returns="streaming"`` generators (tasks for
  stateless stages — lineage-replayable on a mid-stream worker kill —
  or actor-pool members for callable-class UDFs and stream-fed
  stages). A stage member consumes its slice of the upstream items and
  yields one output block per input the moment it exists, so stage
  N+1 starts on stage N's FIRST block instead of after an in-order
  submission window drains. Backpressure is the streaming layer's
  consumer-paced credit window: ``DataContext.
  max_tasks_in_flight_per_operator`` is split across the stage's
  members and mapped onto ``generator_backpressure_num_objects``, so a
  slow consumer blocks the producers at the window instead of flooding
  the object store. Completion order is surfaced via ``wait_any``;
  ``DataContext.preserve_order`` (default True) keeps the submission-
  order yield ``sort``/``limit``/``take`` assume.

``DataContext.execution_mode = "staged"`` selects the serialized
baseline (per-block tasks, in-order window, materialize barrier between
stages) that ``bench.py --data`` measures the streaming executor
against.

All-to-all ops (shuffle/sort/repartition) are barriers, as in the
reference's exchange operators (``planner/exchange/``).
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, _to_table
from ray_tpu.data.context import DataContext


# ---------------------------------------------------------------- ops
@dataclass
class ReadOp:
    """Source: a list of zero-arg callables each producing a Block."""
    tasks: List[Callable[[], Block]]
    name: str = "Read"


@dataclass
class InputDataOp:
    """Source: pre-materialized block refs."""
    block_refs: List[Any]
    name: str = "InputData"


@dataclass
class OneToOneOp:
    """A per-block transform: fn(Block) -> Block. Fusable."""
    fn: Callable[[Block], Block]
    name: str = "Map"
    # actor-pool compute (None = task pool)
    actor_pool_size: Optional[int] = None
    fn_constructor: Optional[Callable[[], Any]] = None
    num_cpus: Optional[float] = None


@dataclass
class AllToAllOp:
    """Barrier op over the full materialized block list."""
    fn: Callable[[List[Any]], List[Any]]  # refs -> refs
    name: str = "AllToAll"


@dataclass
class ExchangeOp:
    """Pipelined all-to-all (reference: planner/exchange/ operators fed
    by the streaming executor): ``run`` receives the upstream ref
    ITERATOR so map-side tasks launch as blocks materialize; only the
    reduce phase barriers. ``count_hint`` is the statically-known
    upstream block count (None after limit/union)."""
    run: Callable[..., List[Any]]  # (ref_iter, count_hint) -> refs
    name: str = "Exchange"
    #: statically-known output block count (repartition(n)); None keeps
    #: the upstream count (shuffle/sort)
    out_count: Optional[int] = None


@dataclass
class LimitOp:
    n: int
    name: str = "Limit"


@dataclass
class UnionOp:
    others: List["ExecutionPlan"]
    name: str = "Union"


class ExecutionPlan:
    def __init__(self, source, ops: Optional[List[Any]] = None):
        self.source = source  # ReadOp | InputDataOp
        self.ops: List[Any] = ops or []

    def with_op(self, op) -> "ExecutionPlan":
        return ExecutionPlan(self.source, self.ops + [op])

    def source_len(self) -> int:
        if isinstance(self.source, ReadOp):
            return len(self.source.tasks)
        return len(self.source.block_refs)

    def __repr__(self):
        names = [getattr(self.source, "name", "?")] + [
            op.name for op in self.ops]
        return " -> ".join(names)


# ----------------------------------------------------------- execution
def _apply_chain(fns: List[Callable[[Block], Block]], item) -> Block:
    """The fused stage body: run a producer or block through the chain
    of one-to-one transforms. Runs remotely, one task per block."""
    block = item() if callable(item) else item
    for fn in fns:
        block = fn(block)
    return block


def _materialize_item(item) -> Block:
    """An upstream item is a ready Block, a read callable, or a block
    ref (nested in the items list, so not auto-resolved)."""
    from ray_tpu.core.object_ref import ObjectRef
    if isinstance(item, ObjectRef):
        return ray_tpu.get(item)
    return item() if callable(item) else item


def _stage_stream(fns: List[Callable], items: List[Any]):
    """Long-lived generator-task stage member: consumes its slice of
    the upstream items and yields one output block per input. Runs as
    ``num_returns="streaming"`` so downstream starts on the first
    yield; deterministic in its args, so a mid-stream worker SIGKILL
    lineage-replays the stream prefix exactly-once."""
    for item in items:
        block = _materialize_item(item)
        for fn in fns:
            block = fn(block)
        yield block


class _ActorStage:
    """Actor holding stateful transform constructors for an actor-pool
    stage (reference ``ActorPoolMapOperator``; callable-class UDFs).
    Used by the ``staged`` baseline executor."""

    def __init__(self, constructors: List[Optional[Callable]]):
        self._instances = [c() if c is not None else None
                           for c in constructors]

    def apply(self, fns: List[Callable], item) -> Block:
        block = item() if callable(item) else item
        for fn, inst in zip(fns, self._instances):
            if inst is not None:
                block = fn(block, inst)
            else:
                block = fn(block)
        return block


class _StageWorker:
    """Long-lived actor-pool stage member for the streaming executor:
    the driver ``feed``s it upstream items (block refs travel as
    top-level args, so the block moves producer→worker peer-to-peer —
    the driver only routes refs) and its ``run`` streaming generator
    applies the fused chain, yielding one output block per input.

    Runs with ``max_concurrency >= 2``: ``run`` blocks on the mailbox
    while ``feed``/``finish`` calls land (same mailbox discipline as
    ``parallel/mpmd_pipeline.PipelineStage``). The mailbox is INDEXED:
    concurrent actor calls are *admitted* in submission order but race
    on the executor threads, so ``feed`` carries its per-worker
    sequence number and ``finish`` the total count — ``run`` processes
    strictly by index and only exits once every fed item is done, so a
    ``finish`` overtaking a late ``feed`` can neither drop nor reorder
    blocks."""

    FEED_TIMEOUT_S = 600.0

    def __init__(self, constructors: Optional[List[Optional[Callable]]]):
        self._instances = [c() if c is not None else None
                           for c in (constructors or [])]
        self._box: Dict[int, Any] = {}
        self._cond = threading.Condition()
        self._expected: Optional[int] = None

    def feed(self, i: int, item) -> None:
        with self._cond:
            self._box[i] = item
            self._cond.notify_all()

    def finish(self, count: int) -> None:
        with self._cond:
            self._expected = count
            self._cond.notify_all()

    def run(self, fns: List[Callable]):
        import time as _time
        i = 0
        while True:
            deadline = _time.monotonic() + self.FEED_TIMEOUT_S
            with self._cond:
                while i not in self._box and \
                        (self._expected is None or i < self._expected):
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            f"stage worker starved waiting for item {i} "
                            f"(driver pump dead?)")
                    self._cond.wait(0.1)
                if i not in self._box:
                    return  # every fed item processed
                item = self._box.pop(i)
            i += 1
            block = item() if callable(item) else item
            if self._instances:
                for fn, inst in zip(fns, self._instances):
                    block = fn(block, inst) if inst is not None \
                        else fn(block)
            else:
                for fn in fns:
                    block = fn(block)
            yield block


def _fuse(ops: List[Any]) -> List[Any]:
    """Group consecutive OneToOneOps with compatible compute into fused
    stages; barrier/limit ops pass through."""
    fused: List[Any] = []
    buf: List[OneToOneOp] = []

    def flush():
        if buf:
            fused.append(list(buf))
            buf.clear()

    prev_pool: Optional[int] = None
    for op in ops:
        if isinstance(op, OneToOneOp):
            if buf and op.actor_pool_size != prev_pool:
                flush()
            buf.append(op)
            prev_pool = op.actor_pool_size
        else:
            flush()
            fused.append(op)
    flush()
    return fused


def execute_streaming(plan: ExecutionPlan,
                      ctx: Optional[DataContext] = None
                      ) -> Iterator[Any]:
    """Yield output block refs. In the default ``streaming`` mode the
    fused stages run as generator pools paced by the credit window; in
    ``staged`` mode, per-block tasks with an in-order window of
    ``ctx.max_tasks_in_flight_per_operator`` and a barrier per stage."""
    ctx = ctx or DataContext.get_current()

    # Source items: callables (read tasks) or ready refs.
    if isinstance(plan.source, ReadOp):
        items: List[Any] = list(plan.source.tasks)
        items_are_refs = False
    else:
        items = list(plan.source.block_refs)
        items_are_refs = True

    stages = _fuse(plan.ops)
    stream = _run_stages(items, items_are_refs, stages, ctx,
                         plan.source_len())
    yield from stream


def _run_stages(items, items_are_refs: bool,
                stages: List[Any], ctx: DataContext,
                count_hint: Optional[int] = None) -> Iterator[Any]:
    streaming = ctx.execution_mode != "staged"
    if not stages:
        # Source only: materialize reads into refs.
        if items_are_refs:
            yield from iter(items)
        elif streaming and isinstance(items, list):
            yield from _run_fused_stage_streaming(
                items, False, [OneToOneOp(lambda b: b, name="Read")],
                ctx)
        else:
            yield from _window_map(
                iter(items), lambda task: _remote_apply([], task), ctx)
        return

    stage, rest = stages[0], stages[1:]
    if isinstance(stage, list):  # fused one-to-one stage
        if streaming:
            out = _run_fused_stage_streaming(items, items_are_refs,
                                             stage, ctx)
        else:
            out = _run_fused_stage(iter(items), items_are_refs, stage,
                                   ctx)
            if rest:
                # staged baseline: a real materialize barrier — pace
                # completions through the in-order window, and only
                # start the next stage once every block exists
                out = _window_barrier(out, ctx)
        yield from _run_stages(out, True, rest, ctx, count_hint)
    elif isinstance(stage, ExchangeOp):
        upstream = _run_stages(items, items_are_refs, [], ctx,
                               count_hint)
        out_refs = stage.run(upstream, count_hint)
        yield from _run_stages(list(out_refs), True, rest, ctx,
                               len(out_refs))
    elif isinstance(stage, AllToAllOp):
        refs = list(_run_stages(items, items_are_refs, [], ctx,
                                count_hint))
        out_refs = stage.fn(refs)
        yield from _run_stages(list(out_refs), True, rest, ctx,
                               len(out_refs))
    elif isinstance(stage, LimitOp):
        out = _run_limit(
            _run_stages(items, items_are_refs, [], ctx, count_hint),
            stage.n)
        # limit truncates an unknown number of blocks: no hint below
        yield from _run_stages(out, True, rest, ctx, None)
    elif isinstance(stage, UnionOp):
        def chained():
            yield from _run_stages(items, items_are_refs, [], ctx,
                                   count_hint)
            for other in stage.others:
                yield from execute_streaming(other, ctx)
        # other branches' output counts aren't statically derived here
        yield from _run_stages(chained(), True, rest, ctx, None)
    else:
        raise TypeError(f"Unknown stage: {stage!r}")


_remote_apply_cached: Dict[float, Any] = {}


def _get_remote_apply(num_cpus: float = 1.0):
    if num_cpus not in _remote_apply_cached:
        _remote_apply_cached[num_cpus] = ray_tpu.remote(
            num_cpus=num_cpus)(_apply_chain)
    return _remote_apply_cached[num_cpus]


def _remote_apply(fns, item, num_cpus: float = 1.0):
    return _get_remote_apply(num_cpus).remote(fns, item)


_stage_stream_cached: Dict[float, Any] = {}


def _get_stage_stream(num_cpus: float = 1.0):
    if num_cpus not in _stage_stream_cached:
        _stage_stream_cached[num_cpus] = ray_tpu.remote(
            num_cpus=num_cpus, num_returns="streaming")(_stage_stream)
    return _stage_stream_cached[num_cpus]


def _window_barrier(refs: Iterator[Any], ctx: DataContext) -> List[Any]:
    """Staged-baseline stage barrier: consume the windowed ref stream
    waiting on each completion in submission order (so the in-order
    window actually bounds in-flight tasks), returning only once the
    whole stage is materialized."""
    out: List[Any] = []
    for ref in refs:
        try:
            ray_tpu.wait([ref], num_returns=1, timeout=600)
        except Exception:
            pass
        out.append(ref)
    return out


def _window_map(items: Iterator[Any], submit: Callable[[Any], Any],
                ctx: DataContext) -> Iterator[Any]:
    """Submit tasks keeping a bounded in-flight window; yield refs in
    order (the ``staged`` baseline's in-order submission window)."""
    window = ctx.max_tasks_in_flight_per_operator
    inflight: List[Any] = []
    for item in items:
        inflight.append(submit(item))
        if len(inflight) >= window:
            yield inflight.pop(0)
    while inflight:
        yield inflight.pop(0)


# ----------------------------------------- streaming (generator-fed)
def _stage_pool_size(stage: List[OneToOneOp], n_items: Optional[int],
                     ctx: DataContext) -> int:
    pool = stage[0].actor_pool_size
    if pool is None:
        pool = ctx.streaming_stage_parallelism \
            or ctx.max_tasks_in_flight_per_operator
    if n_items is not None:
        pool = min(pool, max(n_items, 1))
    return max(1, pool)


def _drain_one(gen, timeout: float = 600.0):
    """Pull the next item ref from a stage stream; None at EOF."""
    try:
        return gen.next_ref(timeout=timeout)
    except StopIteration:
        return None


def _run_fused_stage_streaming(items, items_are_refs: bool,
                               stage: List[OneToOneOp], ctx: DataContext
                               ) -> Iterator[Any]:
    """Run one fused stage as a pool of long-lived streaming
    generators. Static (list) upstreams with task compute become
    lineage-replayable generator TASKS over round-robin slices;
    actor-pool stages and dynamically-fed (stream) upstreams become
    ``_StageWorker`` actors pumped by the driver."""
    fns = [op.fn for op in stage]
    stage_cpus = max((op.num_cpus or 1.0) for op in stage)
    window = max(1, ctx.max_tasks_in_flight_per_operator)
    static = isinstance(items, list)
    pool_cfg = stage[0].actor_pool_size
    n_items = len(items) if static else None
    k = _stage_pool_size(stage, n_items, ctx)
    # ceil(window / k), floored at 2: a window of 1 would cost one
    # credit round-trip per block (yield → stall → credit → yield)
    per_gen_bp = max(2, -(-window // k))

    if static and not items:
        return
    if static and pool_cfg is None:
        yield from _run_static_task_stage(items, fns, stage_cpus, k,
                                          per_gen_bp, ctx)
        return
    constructors = [op.fn_constructor for op in stage] \
        if pool_cfg is not None else None
    yield from _run_fed_actor_stage(
        iter(items), fns, constructors, stage_cpus, k, per_gen_bp,
        window, ctx)


def _run_static_task_stage(items: List[Any], fns, stage_cpus: float,
                           k: int, per_gen_bp: int, ctx: DataContext
                           ) -> Iterator[Any]:
    """k long-lived generator tasks over round-robin item slices."""
    remote_fn = _get_stage_stream(stage_cpus)
    gens = [remote_fn.options(
        generator_backpressure_num_objects=per_gen_bp).remote(
            fns, items[i::k]) for i in range(k)]
    try:
        if ctx.preserve_order:
            yield from _consume_round_robin(gens, len(items))
        else:
            yield from _consume_completion_order(gens)
    finally:
        for g in gens:
            try:
                g.close()
            except Exception:
                pass


def _run_fed_actor_stage(items: Iterator[Any], fns, constructors,
                         stage_cpus: float, k: int, per_gen_bp: int,
                         window: int, ctx: DataContext) -> Iterator[Any]:
    """k ``_StageWorker`` actors fed round-robin by the driver with a
    bounded feed-ahead; outputs drained from their ``run`` streams."""
    actor_cls = ray_tpu.remote(num_cpus=stage_cpus,
                               max_concurrency=4)(_StageWorker)
    workers = [actor_cls.remote(constructors) for _ in range(k)]
    gens = [w.run.options(
        num_returns="streaming",
        generator_backpressure_num_objects=per_gen_bp).remote(fns)
        for w in workers]
    fed = 0
    consumed = 0
    fed_per_worker = [0] * k
    exhausted = False
    feed_ahead = max(window, k)
    try:
        while True:
            while not exhausted and fed - consumed < feed_ahead:
                try:
                    item = next(items)
                except StopIteration:
                    exhausted = True
                    for w, count in zip(workers, fed_per_worker):
                        w.finish.remote(count)
                    break
                wi = fed % k
                workers[wi].feed.remote(fed_per_worker[wi], item)
                fed_per_worker[wi] += 1
                fed += 1
            if exhausted and consumed >= fed:
                break
            if ctx.preserve_order:
                ref = _drain_one(gens[consumed % k])
                if ref is None:
                    raise RuntimeError(
                        f"stage stream {consumed % k} ended early at "
                        f"output {consumed}/{fed}")
                consumed += 1
                yield ref
            else:
                from ray_tpu.core.streaming import wait_any
                active = [g for g in gens if not g.is_finished()]
                if not active:
                    break
                ready, _ = wait_any(active, timeout=600.0)
                if not ready:
                    raise TimeoutError(
                        "fused stage made no progress in 600s")
                got = False
                for g in ready:
                    burst = g.ready_refs()
                    if burst:
                        got = True
                        for ref in burst:
                            consumed += 1
                            yield ref
                    else:
                        # ready with nothing buffered: EOF (consume the
                        # StopIteration so the stream record is freed)
                        # or a failure — surfaced typed right here.
                        _drain_one(g, timeout=0.1)
                if not got and all(g.is_finished() for g in gens):
                    break
    finally:
        for g in gens:
            try:
                g.close()
            except Exception:
                pass
        for w in workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


def _consume_round_robin(gens, total: int) -> Iterator[Any]:
    """Submission-order yield: output j comes from generator j % k
    (items were sliced round-robin), so global order is preserved while
    every member still computes ahead inside its credit window."""
    k = len(gens)
    for j in range(total):
        ref = _drain_one(gens[j % k])
        if ref is None:
            raise RuntimeError(
                f"stage stream {j % k} ended early at output {j}/{total}")
        yield ref


def _consume_completion_order(gens) -> Iterator[Any]:
    """Completion-order yield via ``wait_any``: whichever member has a
    block buffered is drained first, so a straggler never stalls the
    stream."""
    from ray_tpu.core.streaming import wait_any
    pending = list(gens)
    while pending:
        ready, _ = wait_any(pending, timeout=600.0)
        if not ready:
            raise TimeoutError("fused stage made no progress in 600s")
        for g in ready:
            burst = g.ready_refs()
            if burst:
                yield from burst
            else:
                _drain_one(g, timeout=0.1)  # EOF cleanup / typed error
        pending = [g for g in pending if not g.is_finished()]


# ------------------------------------------------- staged baseline
def _run_fused_stage(items: Iterator[Any], items_are_refs: bool,
                     stage: List[OneToOneOp], ctx: DataContext
                     ) -> Iterator[Any]:
    pool_size = stage[0].actor_pool_size
    stage_cpus = max((op.num_cpus or 1.0) for op in stage)
    if pool_size is None:
        fns = [op.fn for op in stage]
        yield from _window_map(
            items, lambda item: _remote_apply(fns, item, stage_cpus), ctx)
        return
    # Actor-pool stage: round-robin blocks over a pool of stage actors.
    constructors = [op.fn_constructor for op in stage]
    fns = [op.fn for op in stage]
    actor_cls = ray_tpu.remote(num_cpus=stage_cpus)(_ActorStage)
    actors = [actor_cls.remote(constructors) for _ in range(pool_size)]
    submitted: List[Any] = []
    try:
        i = 0
        window = max(pool_size * 2, ctx.max_tasks_in_flight_per_operator)
        inflight: List[Any] = []
        for item in items:
            actor = actors[i % pool_size]
            i += 1
            ref = actor.apply.remote(fns, item)
            submitted.append(ref)
            inflight.append(ref)
            if len(inflight) >= window:
                yield inflight.pop(0)
        while inflight:
            yield inflight.pop(0)
    finally:
        # Yielded refs may not have been consumed yet — wait for every
        # submitted task to finish (results outlive the actors in the
        # object store) BEFORE tearing the pool down.
        if submitted:
            try:
                ray_tpu.wait(submitted, num_returns=len(submitted),
                             timeout=600)
            except Exception:
                pass
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _slice_block(block: Block, n: int) -> Block:
    return BlockAccessor(block).slice(0, n)


def _run_limit(refs: Iterator[Any], n: int) -> Iterator[Any]:
    from ray_tpu.data._internal import shuffle as sh
    remaining = n
    rows_fn = sh._r(sh._rows)
    slice_fn = sh._r(_slice_block)
    for ref in refs:
        if remaining <= 0:
            break
        rows = ray_tpu.get(rows_fn.remote(ref))
        if rows <= remaining:
            remaining -= rows
            yield ref
        else:
            yield slice_fn.remote(ref, remaining)
            remaining = 0
