"""All-to-all exchange ops: repartition, random_shuffle, sort.

Reference: ``python/ray/data/_internal/planner/exchange/`` — two-phase
map/reduce exchanges over block refs. Map tasks partition each input
block; reduce tasks concatenate assigned partitions. All phases are
remote tasks; the driver only routes refs.
"""

from __future__ import annotations

import random
from typing import Any, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def _rows(block: Block) -> int:
    return block.num_rows


def _slice_spans(block: Block, spans: List[Tuple[int, int]]) -> List[Block]:
    acc = BlockAccessor(block)
    return [acc.slice(s, e) for s, e in spans]


def _slice_one(block: Block, s: int, e: int) -> Block:
    return BlockAccessor(block).slice(s, e)


def _concat(*blocks: Block) -> Block:
    return BlockAccessor.concat(list(blocks))


def _concat_sorted(key: str, descending: bool, *blocks: Block) -> Block:
    merged = BlockAccessor.concat(list(blocks))
    if merged.num_rows == 0 or key not in merged.column_names:
        return merged
    order = "descending" if descending else "ascending"
    return merged.sort_by([(key, order)])


def _partition_random(block: Block, n: int, seed: Optional[int]) -> List[Block]:
    rng = np.random.default_rng(seed)
    assignment = rng.integers(0, n, block.num_rows)
    acc = BlockAccessor(block)
    return [acc.take(np.nonzero(assignment == i)[0]) for i in range(n)]


def _shuffle_rows(block: Block, seed: Optional[int]) -> Block:
    rng = np.random.default_rng(seed)
    perm = rng.permutation(block.num_rows)
    return BlockAccessor(block).take(perm)


def _partition_by_bounds(block: Block, key: str, bounds: List[Any],
                         descending: bool) -> List[Block]:
    acc = BlockAccessor(block)
    if key not in block.column_names:
        # Schema-less empty block (e.g. a fully-filtered map output):
        # contributes nothing to any partition.
        empty = block.slice(0, 0)
        return [empty for _ in range(len(bounds) + 1)]
    col = block[key].to_numpy(zero_copy_only=False)
    idx = np.searchsorted(np.asarray(bounds), col, side="right")
    if descending:
        idx = len(bounds) - idx
    return [acc.take(np.nonzero(idx == i)[0])
            for i in range(len(bounds) + 1)]


def _sample_keys(block: Block, key: str, k: int) -> List[Any]:
    if key not in block.column_names:
        return []
    col = block[key].to_numpy(zero_copy_only=False)
    if len(col) == 0:
        return []
    rng = np.random.default_rng(0)
    take = rng.choice(len(col), size=min(k, len(col)), replace=False)
    return sorted(col[take].tolist())


_remote_cache = {}


def _r(fn):
    if fn not in _remote_cache:
        _remote_cache[fn] = ray_tpu.remote(num_cpus=1)(fn)
    return _remote_cache[fn]


def repartition(refs: List[Any], num_blocks: int) -> List[Any]:
    """Equal-row re-split (reference ``RepartitionTaskSpec``)."""
    counts = ray_tpu.get([_r(_rows).remote(ref) for ref in refs])
    return _repartition_planned(refs, counts, num_blocks)


def repartition_to_counts(refs: List[Any],
                          counts: List[int]) -> List[Any]:
    """Re-split ``refs`` so output block i has exactly counts[i] rows
    (used by zip to align the right side with the left's layout)."""
    have = ray_tpu.get([_r(_rows).remote(ref) for ref in refs])
    if sum(have) != sum(counts):
        raise ValueError(
            f"Cannot align datasets: {sum(have)} vs {sum(counts)} rows")
    out = []
    ref_i, offset = 0, 0
    for need in counts:
        parts = []
        while need > 0:
            avail = have[ref_i] - offset
            take = min(avail, need)
            if take > 0:
                parts.append(_r(_slice_one).remote(
                    refs[ref_i], offset, offset + take))
                offset += take
                need -= take
            if offset >= have[ref_i] and ref_i + 1 < len(refs):
                ref_i += 1
                offset = 0
            elif avail <= 0:
                break
        out.append(_r(_concat).remote(*parts) if len(parts) != 1
                   else parts[0])
    return out


# ---------------------------------------------------- streaming exchange
# Reference: python/ray/data/_internal/planner/exchange/ — the map phase
# of an exchange runs per input block and the reference's streaming
# executor feeds it blocks as upstream tasks finish. The functions below
# take the upstream REF ITERATOR (not a materialized list): map-side
# tasks launch the moment each block materializes, overlapping upstream
# production; only the reduce phase is a true barrier (inherent to an
# all-to-all). Peak driver state is one ref per partition slice — block
# BYTES live in the object store and spill when the budget is exceeded.

def streaming_random_shuffle(ref_iter, seed: Optional[int] = None,
                             num_blocks: Optional[int] = None,
                             count_hint: Optional[int] = None) -> List[Any]:
    n_out = num_blocks or count_hint
    if n_out is None:
        # unknown upstream cardinality (e.g. after limit): drain first
        refs = list(ref_iter)
        n_out = max(1, len(refs))
        ref_iter = iter(refs)
    n_out = max(1, n_out)
    parts: List[List[Any]] = [[] for _ in range(n_out)]
    for i, ref in enumerate(ref_iter):
        s = None if seed is None else seed + i
        part_refs = _r(_partition_random).options(
            num_returns=n_out).remote(ref, n_out, s)
        if n_out == 1:
            part_refs = [part_refs]
        for j, pr in enumerate(part_refs):
            parts[j].append(pr)
    out = []
    for j, plist in enumerate(parts):
        s = None if seed is None else seed + 10_000 + j
        if not plist:
            out.append(_r(_concat).remote())
            continue
        merged = _r(_concat).remote(*plist)
        out.append(_r(_shuffle_rows).remote(merged, s))
    return out


def streaming_sort(ref_iter, key: str,
                   descending: bool = False) -> List[Any]:
    """Sample-as-they-arrive range sort: the sampling pass overlaps
    upstream production; partitioning starts once bounds are known."""
    refs: List[Any] = []
    sample_refs: List[Any] = []
    for ref in ref_iter:
        refs.append(ref)
        sample_refs.append(_r(_sample_keys).remote(ref, key, 16))
    if not refs:
        return refs
    n_out = len(refs)
    samples = ray_tpu.get(sample_refs)
    flat = sorted(x for s in samples for x in s)
    if not flat:
        return refs
    bounds = [flat[int(len(flat) * (i + 1) / n_out)]
              for i in range(n_out - 1)
              if int(len(flat) * (i + 1) / n_out) < len(flat)]
    n_parts = len(bounds) + 1
    parts: List[List[Any]] = [[] for _ in range(n_parts)]
    for ref in refs:
        part_refs = _r(_partition_by_bounds).options(
            num_returns=n_parts).remote(ref, key, bounds, descending)
        if n_parts == 1:
            part_refs = [part_refs]
        for j, pr in enumerate(part_refs):
            parts[j].append(pr)
    return [_r(_concat_sorted).remote(key, descending, *plist)
            for plist in parts]


def streaming_repartition(ref_iter, num_blocks: int) -> List[Any]:
    """Row counting overlaps upstream production; the span plan and
    slicing run once every count is known."""
    refs: List[Any] = []
    count_refs: List[Any] = []
    for ref in ref_iter:
        refs.append(ref)
        count_refs.append(_r(_rows).remote(ref))
    if not refs:
        return refs
    # reuse the span planner on the materialized (ref, count) lists
    return _repartition_planned(refs, ray_tpu.get(count_refs),
                                num_blocks)


def _repartition_planned(refs: List[Any], counts: List[int],
                         num_blocks: int) -> List[Any]:
    if num_blocks <= 0:
        raise ValueError("num_blocks must be > 0")
    total = sum(counts)
    base, extra = divmod(total, num_blocks)
    targets = [base + (1 if i < extra else 0) for i in range(num_blocks)]
    out_spans: List[List[Tuple[int, Tuple[int, int]]]] = [
        [] for _ in range(num_blocks)]
    ref_i, offset = 0, 0
    for out_i, need in enumerate(targets):
        while need > 0 and ref_i < len(refs):
            avail = counts[ref_i] - offset
            take = min(avail, need)
            if take > 0:
                out_spans[out_i].append((ref_i, (offset, offset + take)))
                offset += take
                need -= take
            if offset >= counts[ref_i]:
                ref_i += 1
                offset = 0
    per_ref_spans: List[List[Tuple[int, int]]] = [[] for _ in refs]
    span_pos = {}
    for out_i, spans in enumerate(out_spans):
        for ref_i, (s, e) in spans:
            span_pos[(out_i, ref_i, s, e)] = len(per_ref_spans[ref_i])
            per_ref_spans[ref_i].append((s, e))
    sliced = []
    for i, spans in enumerate(per_ref_spans):
        if not spans:
            sliced.append(None)
        elif len(spans) == 1:
            s, e = spans[0]
            sliced.append([_r(_slice_one).remote(refs[i], s, e)])
        else:
            sliced.append(_r(_slice_spans).options(
                num_returns=len(spans)).remote(refs[i], spans))

    def span_ref(out_i, ref_i, s, e):
        return sliced[ref_i][span_pos[(out_i, ref_i, s, e)]]

    out = []
    for out_i, spans in enumerate(out_spans):
        part_refs = [span_ref(out_i, ref_i, s, e)
                     for ref_i, (s, e) in spans]
        if not part_refs:
            out.append(_r(_concat).remote())
        elif len(part_refs) == 1:
            out.append(part_refs[0])
        else:
            out.append(_r(_concat).remote(*part_refs))
    return out
