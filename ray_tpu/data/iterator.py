"""Batch iteration + streaming shards for Train workers.

Reference: ``python/ray/data/iterator.py`` (DataIterator) and the
``streaming_split``/``OutputSplitter`` path
(``execution/operators/output_splitter.py``): a coordinator actor feeds
block refs to N shard iterators round-robin, so Train workers pull
blocks as they are produced — no full materialization barrier.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def iter_batches_over_blocks(blocks: Iterator[Block],
                             batch_size: Optional[int],
                             batch_format: str,
                             drop_last: bool = False,
                             shuffle_buffer_size: Optional[int] = None,
                             shuffle_seed: Optional[int] = None
                             ) -> Iterator[Any]:
    """Re-chunk a block stream into fixed-size batches; optional local
    shuffle buffer (reference ``iter_batches`` semantics). Consumed
    blocks' shm reader leases release by REFCOUNT the moment the last
    batch/slice alias dies (the lease anchors on the deserialization
    buffer views — see Runtime._cache_shm_value), so streaming an
    over-budget dataset keeps only the working set pinned."""
    rng = np.random.default_rng(shuffle_seed)
    carry: List[pa.Table] = []
    carry_rows = 0
    buffer: List[pa.Table] = []
    buffer_rows = 0

    def emit(table: pa.Table):
        return BlockAccessor(table).to_batch(batch_format)

    def drain_carry():
        nonlocal carry, carry_rows
        merged = BlockAccessor.concat(carry) if len(carry) != 1 else carry[0]
        carry, carry_rows = [], 0
        return merged

    source: Iterator[pa.Table]
    if shuffle_buffer_size:
        def shuffled() -> Iterator[pa.Table]:
            nonlocal buffer, buffer_rows
            for b in blocks:
                buffer.append(b)
                buffer_rows += b.num_rows
                while buffer_rows >= shuffle_buffer_size:
                    merged = BlockAccessor.concat(buffer)
                    perm = rng.permutation(merged.num_rows)
                    merged = BlockAccessor(merged).take(perm)
                    half = merged.num_rows // 2
                    yield merged.slice(0, half)
                    buffer = [merged.slice(half)]
                    buffer_rows = merged.num_rows - half
            if buffer:
                merged = BlockAccessor.concat(buffer)
                perm = rng.permutation(merged.num_rows)
                yield BlockAccessor(merged).take(perm)
        source = shuffled()
    else:
        source = blocks

    if batch_size is None:
        for b in source:
            if b.num_rows:
                yield emit(b)
        return

    for b in source:
        if b.num_rows == 0:
            continue
        carry.append(b)
        carry_rows += b.num_rows
        while carry_rows >= batch_size:
            merged = drain_carry()
            n_full = merged.num_rows // batch_size
            for i in range(n_full):
                yield emit(merged.slice(i * batch_size, batch_size))
            rest = merged.num_rows - n_full * batch_size
            if rest:
                carry = [merged.slice(n_full * batch_size)]
                carry_rows = rest
    if carry_rows and not drop_last:
        yield emit(drain_carry())


class _SplitCoordinator:
    """Actor that routes block refs to shards, balancing assigned ROWS
    greedily (imbalance bounded by one block) so lockstep SPMD consumers
    stay within a block of each other (reference ``OutputSplitter``).
    Only refs flow through the coordinator — blocks move peer-to-peer
    from producer tasks to consuming workers."""

    def __init__(self, plan_holder, n: int, equal: bool):
        ds = plan_holder()
        self._it = ds.iter_block_refs()
        self._n = n
        self._equal = equal
        self._queues = [collections.deque() for _ in range(n)]
        self._rows = [0] * n
        self._exhausted = False
        self._next_shard = 0

    def _assign_one(self) -> bool:
        try:
            ref = next(self._it)
        except StopIteration:
            self._exhausted = True
            return False
        if self._equal:
            from ray_tpu.data._internal import shuffle as sh
            nrows = ray_tpu.get(sh._r(sh._rows).remote(ref))
            shard = min(range(self._n), key=lambda i: self._rows[i])
            self._rows[shard] += nrows
        else:
            shard = self._next_shard
            self._next_shard = (self._next_shard + 1) % self._n
        self._queues[shard].append(ref)
        return True

    def next_block_ref(self, shard_id: int):
        """Returns the next block REF for this shard, or None when the
        stream is exhausted."""
        q = self._queues[shard_id]
        while not q and not self._exhausted:
            self._assign_one()
        if not q:
            return None
        return q.popleft()


class DataIterator:
    """Per-worker shard handle; picklable (holds an actor handle)."""

    def __init__(self, coordinator, shard_id: int):
        self._coordinator = coordinator
        self._shard_id = shard_id

    def _iter_blocks(self) -> Iterator[Block]:
        while True:
            ref = ray_tpu.get(
                self._coordinator.next_block_ref.remote(self._shard_id))
            if ref is None:
                return
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     **_ignored) -> Iterator[Any]:
        yield from iter_batches_over_blocks(
            self._iter_blocks(), batch_size, batch_format, drop_last,
            local_shuffle_buffer_size, local_shuffle_seed)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def materialize(self):
        from ray_tpu.data.dataset import MaterializedDataset
        from ray_tpu.data._internal.plan import ExecutionPlan, InputDataOp
        refs = [ray_tpu.put(b) for b in self._iter_blocks()]
        return MaterializedDataset(ExecutionPlan(InputDataOp(refs)))


def make_streaming_shards(ds, n: int, equal: bool = True
                          ) -> List[DataIterator]:
    plan = ds._plan

    def plan_holder():
        from ray_tpu.data.dataset import Dataset
        return Dataset(plan)

    coord_cls = ray_tpu.remote(num_cpus=0.0)(_SplitCoordinator)
    coordinator = coord_cls.remote(plan_holder, n, equal)
    return [DataIterator(coordinator, i) for i in range(n)]
