"""Batch iteration + streaming shards for Train workers.

Reference: ``python/ray/data/iterator.py`` (DataIterator) and the
``streaming_split``/``OutputSplitter`` path
(``execution/operators/output_splitter.py``): a coordinator actor feeds
block refs to N shard iterators, so Train workers pull blocks as they
are produced — no full materialization barrier.

The consumer edge is non-blocking end to end: each shard consumes a
``stream_shard`` streaming generator (one ``num_returns="streaming"``
call per shard, refs pushed as they are assigned) instead of one
blocking ``next_block_ref`` RPC per block, and ``iter_batches`` keeps
``prefetch_batches`` resolved blocks in flight on a background thread
so block materialization happens off the consume path. The equal-split
balancer pipelines its row-count lookups
(``DataContext.split_count_pipeline_depth`` refs ahead), so balancing
never stalls the shard stream on a blocking per-block count.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext


def iter_batches_over_blocks(blocks: Iterator[Block],
                             batch_size: Optional[int],
                             batch_format: str,
                             drop_last: bool = False,
                             shuffle_buffer_size: Optional[int] = None,
                             shuffle_seed: Optional[int] = None
                             ) -> Iterator[Any]:
    """Re-chunk a block stream into fixed-size batches; optional local
    shuffle buffer (reference ``iter_batches`` semantics). Consumed
    blocks' shm reader leases release by REFCOUNT the moment the last
    batch/slice alias dies (the lease anchors on the deserialization
    buffer views — see Runtime._cache_shm_value), so streaming an
    over-budget dataset keeps only the working set pinned."""
    rng = np.random.default_rng(shuffle_seed)
    carry: List[pa.Table] = []
    carry_rows = 0
    buffer: List[pa.Table] = []
    buffer_rows = 0

    def emit(table: pa.Table):
        return BlockAccessor(table).to_batch(batch_format)

    def drain_carry():
        nonlocal carry, carry_rows
        merged = BlockAccessor.concat(carry) if len(carry) != 1 else carry[0]
        carry, carry_rows = [], 0
        return merged

    source: Iterator[pa.Table]
    if shuffle_buffer_size:
        def shuffled() -> Iterator[pa.Table]:
            nonlocal buffer, buffer_rows
            for b in blocks:
                buffer.append(b)
                buffer_rows += b.num_rows
                while buffer_rows >= shuffle_buffer_size:
                    merged = BlockAccessor.concat(buffer)
                    perm = rng.permutation(merged.num_rows)
                    merged = BlockAccessor(merged).take(perm)
                    half = merged.num_rows // 2
                    yield merged.slice(0, half)
                    buffer = [merged.slice(half)]
                    buffer_rows = merged.num_rows - half
            if buffer:
                merged = BlockAccessor.concat(buffer)
                perm = rng.permutation(merged.num_rows)
                yield BlockAccessor(merged).take(perm)
        source = shuffled()
    else:
        source = blocks

    if batch_size is None:
        for b in source:
            if b.num_rows:
                yield emit(b)
        return

    for b in source:
        if b.num_rows == 0:
            continue
        carry.append(b)
        carry_rows += b.num_rows
        while carry_rows >= batch_size:
            merged = drain_carry()
            n_full = merged.num_rows // batch_size
            for i in range(n_full):
                yield emit(merged.slice(i * batch_size, batch_size))
            rest = merged.num_rows - n_full * batch_size
            if rest:
                carry = [merged.slice(n_full * batch_size)]
                carry_rows = rest
    if carry_rows and not drop_last:
        yield emit(drain_carry())


class _PrefetchFailed:
    """Error capsule crossing the prefetch thread → consumer boundary."""

    def __init__(self, err: BaseException):
        self.err = err


_PREFETCH_EOF = "__prefetch_eof__"


def prefetch_blocks(refs: Iterator[Any], prefetch: int,
                    stats: Optional[Dict[str, int]] = None
                    ) -> Iterator[Block]:
    """Materialize a block-ref stream keeping up to ``prefetch``
    resolved blocks ahead of the consumer on a background thread. A
    consume-time ``get`` that finds its block already resolved is a
    prefetch *hit*; having to wait is a *miss* (``stats`` accumulates
    both). ``prefetch <= 0`` degrades to inline resolution."""
    if stats is None:
        stats = {}
    stats.setdefault("hits", 0)
    stats.setdefault("misses", 0)
    if prefetch <= 0:
        for ref in refs:
            stats["misses"] += 1
            yield ray_tpu.get(ref)
        return

    import queue as _queue
    q: "_queue.Queue" = _queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def _pump():
        try:
            for ref in refs:
                if not _put(ray_tpu.get(ref)):
                    return
            _put(_PREFETCH_EOF)
        except BaseException as e:  # noqa: BLE001 — crosses threads
            _put(_PrefetchFailed(e))

    t = threading.Thread(target=_pump, daemon=True,
                         name="ray-tpu-data-prefetch")
    t.start()
    try:
        while True:
            try:
                item = q.get_nowait()
                stats["hits"] += 1
            except _queue.Empty:
                stats["misses"] += 1
                item = q.get()
            if item is _PREFETCH_EOF:
                return
            if isinstance(item, _PrefetchFailed):
                raise item.err
            yield item
    finally:
        stop.set()


class _SplitCoordinator:
    """Actor that routes block refs to shards, balancing assigned ROWS
    greedily (imbalance bounded by one block) so lockstep SPMD consumers
    stay within a block of each other (reference ``OutputSplitter``).
    Only refs flow through the coordinator — blocks move peer-to-peer
    from producer tasks to consuming workers.

    Each shard consumes a ``stream_shard`` streaming generator; the
    shard streams run concurrently on the actor's executor threads
    (``make_streaming_shards`` sizes ``max_concurrency`` for n shards),
    so one lock guards the shared assignment state. Row counts for the
    equal split are PIPELINED: a lookahead of count tasks rides
    ``split_count_pipeline_depth`` refs ahead of assignment, so the
    balancer reads a count that is (almost always) already resolved
    instead of stalling the stream on a blocking per-block RPC."""

    def __init__(self, plan_holder, n: int, equal: bool):
        ds = plan_holder()
        self._it = ds.iter_block_refs()
        self._n = n
        self._equal = equal
        self._queues = [collections.deque() for _ in range(n)]
        self._rows = [0] * n
        self._exhausted = False
        self._next_shard = 0
        self._lock = threading.Lock()
        #: (block_ref, count_ref|None) lookahead — counts in flight
        self._pending: collections.deque = collections.deque()
        self._count_depth = max(
            1, DataContext.get_current().split_count_pipeline_depth)

    # ------------------------------------------------- assignment core
    def _refill_pending(self) -> None:
        """Top the lookahead up: pull upstream refs and launch their
        count tasks so the counts resolve while earlier blocks are
        being assigned/consumed."""
        from ray_tpu.data._internal import shuffle as sh
        while not self._exhausted \
                and len(self._pending) < self._count_depth:
            try:
                ref = next(self._it)
            except StopIteration:
                self._exhausted = True
                return
            count_ref = sh._r(sh._rows).remote(ref) if self._equal \
                else None
            self._pending.append((ref, count_ref))

    def _assign_one(self) -> bool:
        self._refill_pending()
        if not self._pending:
            return False
        ref, count_ref = self._pending.popleft()
        self._refill_pending()  # keep counts in flight while we wait
        if self._equal:
            nrows = ray_tpu.get(count_ref)
            shard = min(range(self._n), key=lambda i: self._rows[i])
            self._rows[shard] += nrows
        else:
            shard = self._next_shard
            self._next_shard = (self._next_shard + 1) % self._n
        self._queues[shard].append(ref)
        return True

    def _next_for(self, shard_id: int):
        with self._lock:
            q = self._queues[shard_id]
            while not q:
                if not self._assign_one():
                    return None
            return q.popleft()

    # ---------------------------------------------------- consumer edge
    def stream_shard(self, shard_id: int):
        """Streaming generator of this shard's block refs (consumed via
        ``num_returns="streaming"``): refs are pushed to the consumer
        as they are assigned, replacing one blocking ``next_block_ref``
        RPC per block."""
        while True:
            ref = self._next_for(shard_id)
            if ref is None:
                return
            yield ref

    def next_block_ref(self, shard_id: int):
        """Legacy pull edge: one blocking RPC per block. Returns the
        next block REF for this shard, or None when the stream is
        exhausted."""
        return self._next_for(shard_id)

    def shard_rows(self) -> List[int]:
        """Rows assigned per shard so far (equal-split balance probe)."""
        with self._lock:
            return list(self._rows)


class DataIterator:
    """Per-worker shard handle; picklable (holds an actor handle)."""

    def __init__(self, coordinator, shard_id: int):
        self._coordinator = coordinator
        self._shard_id = shard_id
        self._prefetch_stats: Dict[str, int] = {"hits": 0, "misses": 0}

    def _iter_block_refs(self) -> Iterator[Any]:
        """Consume this shard's ref stream (each stream item is an
        ObjectRef to a block — blocks stay peer-to-peer)."""
        ctx = DataContext.get_current()
        gen = self._coordinator.stream_shard.options(
            num_returns="streaming",
            generator_backpressure_num_objects=max(
                4, 2 * max(ctx.prefetch_batches, 1)),
        ).remote(self._shard_id)
        try:
            for item_ref in gen:
                yield ray_tpu.get(item_ref)
        finally:
            gen.close()

    def _iter_blocks(self, prefetch_batches: Optional[int] = None
                     ) -> Iterator[Block]:
        ctx = DataContext.get_current()
        prefetch = ctx.prefetch_batches if prefetch_batches is None \
            else prefetch_batches
        yield from prefetch_blocks(self._iter_block_refs(), prefetch,
                                   self._prefetch_stats)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None,
                     prefetch_batches: Optional[int] = None,
                     **_ignored) -> Iterator[Any]:
        """``prefetch_batches`` (default ``DataContext.
        prefetch_batches`` = 2) keeps that many RESOLVED blocks ahead
        of the consume path; 0 resolves inline (the old behavior)."""
        yield from iter_batches_over_blocks(
            self._iter_blocks(prefetch_batches), batch_size,
            batch_format, drop_last, local_shuffle_buffer_size,
            local_shuffle_seed)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def prefetch_stats(self) -> Dict[str, int]:
        """Cumulative prefetch hit/miss counters for this shard (a hit
        = the next block was already resolved when the consumer asked
        for it)."""
        return dict(self._prefetch_stats)

    def materialize(self):
        from ray_tpu.data.dataset import MaterializedDataset
        from ray_tpu.data._internal.plan import ExecutionPlan, InputDataOp
        # Reuse the existing block refs — no copy of every block
        # through this process's memory and back into the store.
        refs = list(self._iter_block_refs())
        mds = MaterializedDataset(ExecutionPlan(InputDataOp(refs)))
        # The coordinator owns the blocks; pin it for the refs' lifetime.
        mds._ref_owner = self._coordinator
        return mds


def make_streaming_shards(ds, n: int, equal: bool = True
                          ) -> List[DataIterator]:
    plan = ds._plan

    def plan_holder():
        from ray_tpu.data.dataset import Dataset
        return Dataset(plan)

    # n shard streams run concurrently on the coordinator's executor
    # threads (+ slack for metadata RPCs like shard_rows).
    coord_cls = ray_tpu.remote(num_cpus=0.0,
                               max_concurrency=n + 4)(_SplitCoordinator)
    coordinator = coord_cls.remote(plan_holder, n, equal)
    return [DataIterator(coordinator, i) for i in range(n)]
