// ray_tpu C++ store client: put/get raw buffers in a node's shared
// -memory object store from native code.
//
// Reference: the reference ships a C++ public API (``cpp/`` — Put/Get
// over the core worker). The TPU-native runtime keeps tasks/actors
// Python-side (specs travel as pickles), so the C++ surface targets
// what native code actually needs on a TPU host: zero-copy access to
// the object store — e.g. a C++ data loader producing blocks that
// Python tasks consume, or a native consumer mapping results without
// copies. Header-only over the same extern-C ABI the Python ctypes
// client uses (store.cpp), so both languages share one allocator,
// reader ledger, and crash-reap semantics.
//
// Usage:
//   ray::tpu::StoreClient store("/dev/shm/raytpu-<session>-<node>.seg");
//   auto id = ray::tpu::ObjectId::FromHex("...28-byte hex...");
//   store.Put(id, data, size);
//   ray::tpu::ObjectView v = store.Get(id);   // zero-copy, leased
//   ...
//   v.Release();  // or let the destructor release
//
// Interop: Python sees these objects via the normal runtime once their
// ids are announced (ray_tpu.core.native_store.NativeShmClient reads
// the same segment); ids are exchanged out of band (e.g. the KV API).

#pragma once

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <unistd.h>

namespace ray {
namespace tpu {

extern "C" {
void* ns_open(const char* path);
void ns_close(void* handle);
uint64_t ns_alloc(void* handle, const uint8_t* id, uint64_t size);
uint64_t ns_seal(void* handle, const uint8_t* id);
uint32_t ns_lookup(void* handle, const uint8_t* id, uint64_t* off,
                   uint64_t* size);
uint32_t ns_acquire(void* handle, const uint8_t* id, int32_t pid,
                    uint64_t* off, uint64_t* size);
void ns_release(void* handle, const uint8_t* id, int32_t pid);
void ns_release_all(void* handle, int32_t pid);
void* ns_base(void* handle);
uint64_t ns_evict(void* handle, const uint8_t* id);
}

constexpr uint32_t kIdLen = 28;
constexpr uint64_t kFull = ~0ULL;
constexpr uint64_t kExists = ~0ULL - 1;

struct ObjectId {
  uint8_t bytes[kIdLen];

  static ObjectId FromHex(const std::string& hex) {
    if (hex.size() != kIdLen * 2)
      throw std::invalid_argument("object id hex must be 56 chars");
    ObjectId id;
    for (uint32_t i = 0; i < kIdLen; i++)
      id.bytes[i] = static_cast<uint8_t>(
          std::stoul(hex.substr(i * 2, 2), nullptr, 16));
    return id;
  }

  std::string Hex() const {
    static const char* d = "0123456789abcdef";
    std::string out(kIdLen * 2, '0');
    for (uint32_t i = 0; i < kIdLen; i++) {
      out[i * 2] = d[bytes[i] >> 4];
      out[i * 2 + 1] = d[bytes[i] & 0xf];
    }
    return out;
  }
};

class StoreClient;

// Zero-copy leased view of a sealed object. Holds a reader reference
// in the shared ledger (the extent cannot be evicted, spilled, or
// compacted away underneath it); released on destruction. Leases of
// crashed processes are reaped by the node manager.
class ObjectView {
 public:
  ObjectView() = default;
  ObjectView(const ObjectView&) = delete;
  ObjectView& operator=(const ObjectView&) = delete;
  ObjectView(ObjectView&& o) noexcept { *this = std::move(o); }
  ObjectView& operator=(ObjectView&& o) noexcept {
    Release();
    handle_ = o.handle_;
    id_ = o.id_;
    data_ = o.data_;
    size_ = o.size_;
    o.handle_ = nullptr;
    o.data_ = nullptr;
    return *this;
  }
  ~ObjectView() { Release(); }

  const uint8_t* data() const { return data_; }
  uint64_t size() const { return size_; }
  bool valid() const { return data_ != nullptr; }

  void Release() {
    if (handle_ != nullptr && data_ != nullptr) {
      ns_release(handle_, id_.bytes, static_cast<int32_t>(getpid()));
      data_ = nullptr;
      handle_ = nullptr;
    }
  }

 private:
  friend class StoreClient;
  void* handle_ = nullptr;
  ObjectId id_{};
  const uint8_t* data_ = nullptr;
  uint64_t size_ = 0;
};

class StoreClient {
 public:
  explicit StoreClient(const std::string& segment_path) {
    handle_ = ns_open(segment_path.c_str());
    if (handle_ == nullptr)
      throw std::runtime_error("cannot open segment " + segment_path);
    base_ = static_cast<uint8_t*>(ns_base(handle_));
  }
  StoreClient(const StoreClient&) = delete;
  StoreClient& operator=(const StoreClient&) = delete;
  ~StoreClient() {
    if (handle_ != nullptr) {
      ns_release_all(handle_, static_cast<int32_t>(getpid()));
      ns_close(handle_);
    }
  }

  // Create + write + seal in one call. Throws on duplicate id; returns
  // false when the store cannot admit the object right now (caller
  // should make room / retry — the Python node manager's background
  // eviction works toward the budget).
  bool Put(const ObjectId& id, const void* data, uint64_t size) {
    uint64_t off = ns_alloc(handle_, id.bytes, size);
    if (off == kExists) throw std::runtime_error("object exists");
    if (off == kFull) return false;
    std::memcpy(base_ + off, data, size);
    ns_seal(handle_, id.bytes);
    return true;
  }

  bool Contains(const ObjectId& id) const {
    uint64_t off = 0, size = 0;
    return ns_lookup(handle_, id.bytes, &off, &size) == 2;
  }

  // Zero-copy leased view; invalid() when the object is not sealed
  // here (spilled objects are restored by the Python runtime paths).
  ObjectView Get(const ObjectId& id) {
    uint64_t off = 0, size = 0;
    uint32_t state = ns_acquire(handle_, id.bytes,
                                static_cast<int32_t>(getpid()), &off,
                                &size);
    ObjectView v;
    if (state != 2) return v;
    v.handle_ = handle_;
    v.id_ = id;
    v.data_ = base_ + off;
    v.size_ = size;
    return v;
  }

  // Owner-side eager free (refuses under live readers); returns freed
  // bytes.
  uint64_t Evict(const ObjectId& id) {
    return ns_evict(handle_, id.bytes);
  }

 private:
  void* handle_ = nullptr;
  uint8_t* base_ = nullptr;
};

}  // namespace tpu
}  // namespace ray
