// Native shared-memory object store: the plasma-equivalent data plane.
//
// Reference analog: src/ray/object_manager/plasma/{store.cc,
// plasma_allocator.h, dlmalloc.cc} — objects live inside ONE mmap'd
// segment; an in-segment index (open-addressed hash of 28-byte object
// ids -> extent) plus a process-shared mutex make create/seal/lookup a
// handful of shared-memory ops instead of per-object file syscalls.
// The Python layer keeps eviction/spill policy (like the raylet owns
// plasma's lifecycle); this file is the allocator + index + views.
//
// Build: g++ -O2 -shared -fPIC -o libnativestore.so store.cpp -lpthread
// ABI: every function is extern "C", loaded via ctypes.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <vector>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055'53544f52ULL;  // "RTPUSTOR"
constexpr uint32_t kMaxFree = 4096;
constexpr uint64_t kAlign = 64;
constexpr uint32_t kIdLen = 28;

// Slot states
constexpr uint32_t kFree = 0;
constexpr uint32_t kBuilding = 1;
constexpr uint32_t kSealed = 2;
constexpr uint32_t kZombie = 3;  // deleted while readers hold views

constexpr uint32_t kMaxReaders = 8192;

struct Slot {
  uint8_t id[kIdLen];
  uint64_t off;    // relative to data_off
  uint64_t size;
  uint32_t state;
  uint32_t probe;  // nonzero if the slot was ever used (tombstones keep
                   // probe chains intact after delete)
  uint32_t refcnt;  // live zero-copy readers (plasma client refs)
  uint32_t pad;
};

// Crash-safe reader ledger: acquires are keyed by (pid, slot), so the
// node manager can reap references held by processes that died without
// releasing (plasma's disconnected-client cleanup).
struct Reader {
  int32_t pid;    // 0 = free entry
  uint32_t slot;  // slot index
  uint32_t count;
  uint32_t pad;
};

struct FreeExtent {
  uint64_t off;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t total_size;  // whole segment incl. header
  uint64_t capacity;    // data area bytes
  uint64_t data_off;
  uint64_t bump;        // high-water mark within data area
  uint64_t used;
  uint32_t nslots;
  uint32_t nfree;
  uint32_t nobjects;
  uint32_t pad;
  pthread_mutex_t mutex;
  // Slots then free extents follow.
};

struct Handle {
  int fd;
  uint8_t* base;
  uint64_t mapped;
  Header* hdr;
  Slot* slots;
  FreeExtent* freelist;
  Reader* readers;
};

uint64_t HashId(const uint8_t* id) {
  // FNV-1a over the 28-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

uint64_t AlignUp(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

void Free(Handle* h, uint64_t off, uint64_t size);
void FreeSlot(Handle* h, Slot* s);

// A process died holding the lock, possibly mid-mutation: the slot table
// is the source of truth (each slot is written id-first, state-last), so
// rebuild every piece of derived allocator state from it — drop slots
// with impossible geometry, recompute bump/used/nobjects, reconstruct the
// freelist from the gaps between live extents, and clear reader-ledger
// entries that point at freed/corrupt slots. Anything the dead process
// half-allocated but never published in a slot is reclaimed by the
// recomputed bump/freelist.
void RecoverAllocator(Handle* h) {
  Header* hdr = h->hdr;
  uint32_t nlive = 0;
  FreeExtent* live = new FreeExtent[hdr->nslots];
  uint64_t used = 0;
  uint32_t nobjects = 0;
  for (uint32_t i = 0; i < hdr->nslots; i++) {
    Slot* s = &h->slots[i];
    if (s->state == kFree) continue;
    uint64_t asize = AlignUp(s->size ? s->size : 1);
    if (s->state > kZombie || s->off > hdr->capacity ||
        asize > hdr->capacity - s->off) {
      // torn write: demote to tombstone (probe chain stays intact)
      s->state = kFree;
      s->probe = 1;
      s->refcnt = 0;
      continue;
    }
    live[nlive].off = s->off;
    live[nlive].size = asize;
    nlive++;
    used += asize;
    if (s->state == kBuilding || s->state == kSealed) nobjects++;
  }
  std::sort(live, live + nlive,
            [](const FreeExtent& a, const FreeExtent& b) {
              return a.off < b.off;
            });
  uint64_t cursor = 0;
  uint32_t nfree = 0;
  for (uint32_t i = 0; i < nlive; i++) {
    if (live[i].off > cursor && nfree < kMaxFree) {
      h->freelist[nfree].off = cursor;
      h->freelist[nfree].size = live[i].off - cursor;
      nfree++;
    }
    uint64_t end = live[i].off + live[i].size;
    if (end > cursor) cursor = end;
  }
  hdr->bump = cursor;
  hdr->nfree = nfree;
  hdr->used = used;
  hdr->nobjects = nobjects;
  delete[] live;
  // Rebuild per-slot refcounts from the ledger (a crash between the
  // ledger increment and the slot increment would otherwise skew them
  // forever), then reclaim zombies nobody references anymore.
  for (uint32_t i = 0; i < hdr->nslots; i++) {
    if (h->slots[i].state != kFree) h->slots[i].refcnt = 0;
  }
  for (uint32_t i = 0; i < kMaxReaders; i++) {
    Reader* r = &h->readers[i];
    if (r->pid == 0) continue;
    if (r->slot >= hdr->nslots || h->slots[r->slot].state == kFree) {
      r->pid = 0;
      r->count = 0;
      continue;
    }
    h->slots[r->slot].refcnt += r->count;
  }
  for (uint32_t i = 0; i < hdr->nslots; i++) {
    Slot* s = &h->slots[i];
    if (s->state == kZombie && s->refcnt == 0) FreeSlot(h, s);
  }
}

class Locker {
 public:
  explicit Locker(Handle* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->hdr->mutex);
    if (rc == EOWNERDEAD) {
      // A crashed process died holding the lock mid-critical-section:
      // rebuild derived allocator state from the slot table before
      // declaring the mutex consistent.
      RecoverAllocator(h_);
      pthread_mutex_consistent(&h_->hdr->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->hdr->mutex); }

 private:
  Handle* h_;
};

Slot* FindSlot(Handle* h, const uint8_t* id, bool find_empty) {
  Header* hdr = h->hdr;
  uint64_t idx = HashId(id) % hdr->nslots;
  Slot* first_tomb = nullptr;
  for (uint32_t i = 0; i < hdr->nslots; i++) {
    Slot* s = &h->slots[(idx + i) % hdr->nslots];
    if (s->state == kFree) {
      if (s->probe == 0) {
        // End of probe chain.
        if (find_empty) return first_tomb ? first_tomb : s;
        return nullptr;
      }
      if (find_empty && first_tomb == nullptr) first_tomb = s;
      continue;  // tombstone: keep probing
    }
    if (memcmp(s->id, id, kIdLen) == 0) return s;
  }
  return find_empty ? first_tomb : nullptr;
}

// Allocate from free list (first fit) or bump. Returns relative offset
// or UINT64_MAX.
uint64_t Alloc(Handle* h, uint64_t size) {
  Header* hdr = h->hdr;
  for (uint32_t i = 0; i < hdr->nfree; i++) {
    FreeExtent* e = &h->freelist[i];
    if (e->size >= size) {
      uint64_t off = e->off;
      e->off += size;
      e->size -= size;
      if (e->size == 0) {
        h->freelist[i] = h->freelist[hdr->nfree - 1];
        hdr->nfree--;
      }
      return off;
    }
  }
  if (hdr->bump + size > hdr->capacity) return UINT64_MAX;
  uint64_t off = hdr->bump;
  hdr->bump += size;
  return off;
}

void Free(Handle* h, uint64_t off, uint64_t size) {
  Header* hdr = h->hdr;
  // Coalesce with an adjacent extent if possible.
  for (uint32_t i = 0; i < hdr->nfree; i++) {
    FreeExtent* e = &h->freelist[i];
    if (e->off + e->size == off) {
      e->size += size;
      return;
    }
    if (off + size == e->off) {
      e->off = off;
      e->size += size;
      return;
    }
  }
  if (off + size == hdr->bump) {  // give back to the bump region
    hdr->bump = off;
    return;
  }
  if (hdr->nfree < kMaxFree) {
    h->freelist[hdr->nfree].off = off;
    h->freelist[hdr->nfree].size = size;
    hdr->nfree++;
  }
  // else: extent leaks until the session ends (bounded by kMaxFree
  // fragmentation; acceptable for a session-scoped store).
}

Handle* MapSegment(int fd, uint64_t total) {
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle();
  h->fd = fd;
  h->base = static_cast<uint8_t*>(base);
  h->mapped = total;
  h->hdr = reinterpret_cast<Header*>(base);
  h->slots = reinterpret_cast<Slot*>(h->base + sizeof(Header));
  h->freelist = reinterpret_cast<FreeExtent*>(
      h->base + sizeof(Header) + sizeof(Slot) * h->hdr->nslots);
  h->readers = reinterpret_cast<Reader*>(
      h->base + sizeof(Header) + sizeof(Slot) * h->hdr->nslots +
      sizeof(FreeExtent) * kMaxFree);
  return h;
}

// Free a zombie slot's extent once its last reader releases.
void FreeSlot(Handle* h, Slot* s) {
  uint64_t asize = AlignUp(s->size ? s->size : 1);
  Free(h, s->off, asize);
  s->state = kFree;  // probe stays 1: tombstone
  h->hdr->used -= asize;
}

constexpr uint32_t kProbeWindow = 128;

Reader* FindReader(Handle* h, int32_t pid, uint32_t slot_idx,
                   bool create) {
  // Fixed probe window, scanned fully by both find and create, so a
  // create and its later find always agree on the entry.
  uint64_t start = ((uint64_t)pid * 2654435761ULL + slot_idx) % kMaxReaders;
  Reader* free_entry = nullptr;
  for (uint32_t i = 0; i < kProbeWindow; i++) {
    Reader* r = &h->readers[(start + i) % kMaxReaders];
    if (r->pid == pid && r->slot == slot_idx && r->count > 0) return r;
    if (r->pid == 0 && free_entry == nullptr) free_entry = r;
  }
  if (create && free_entry != nullptr) {
    free_entry->pid = pid;
    free_entry->slot = slot_idx;
    free_entry->count = 0;
    return free_entry;
  }
  return nullptr;
}

}  // namespace

extern "C" {

// Create the segment file. Returns handle or null.
void* ns_create(const char* path, uint64_t capacity, uint32_t nslots) {
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  uint64_t meta = sizeof(Header) + sizeof(Slot) * (uint64_t)nslots +
                  sizeof(FreeExtent) * (uint64_t)kMaxFree +
                  sizeof(Reader) * (uint64_t)kMaxReaders;
  meta = AlignUp(meta);
  uint64_t total = meta + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  Handle* h;
  {
    void* base =
        mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (base == MAP_FAILED) {
      close(fd);
      unlink(path);
      return nullptr;
    }
    Header* hdr = static_cast<Header*>(base);
    memset(hdr, 0, sizeof(Header));
    hdr->total_size = total;
    hdr->capacity = capacity;
    hdr->data_off = meta;
    hdr->nslots = nslots;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    // Slots/freelist are already zero (fresh file pages).
    hdr->magic = kMagic;  // publish last
    h = new Handle();
    h->fd = fd;
    h->base = static_cast<uint8_t*>(base);
    h->mapped = total;
    h->hdr = hdr;
    h->slots = reinterpret_cast<Slot*>(h->base + sizeof(Header));
    h->freelist = reinterpret_cast<FreeExtent*>(
        h->base + sizeof(Header) + sizeof(Slot) * nslots);
    h->readers = reinterpret_cast<Reader*>(
        h->base + sizeof(Header) + sizeof(Slot) * nslots +
        sizeof(FreeExtent) * kMaxFree);
  }
  return h;
}

// Open an existing segment. Returns handle or null.
void* ns_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  // Map header first to learn the total size.
  void* probe = mmap(nullptr, sizeof(Header), PROT_READ, MAP_SHARED, fd, 0);
  if (probe == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* hdr = static_cast<Header*>(probe);
  if (hdr->magic != kMagic) {
    munmap(probe, sizeof(Header));
    close(fd);
    return nullptr;
  }
  uint64_t total = hdr->total_size;
  munmap(probe, sizeof(Header));
  return MapSegment(fd, total);
}

// Reserve space for an object. Returns ABSOLUTE offset into the
// segment, or UINT64_MAX (full) / UINT64_MAX-1 (already exists).
uint64_t ns_alloc(void* handle, const uint8_t* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(handle);
  uint64_t asize = AlignUp(size ? size : 1);
  Locker lock(h);
  Slot* existing = FindSlot(h, id, false);
  if (existing != nullptr) return UINT64_MAX - 1;
  Slot* s = FindSlot(h, id, true);
  if (s == nullptr) return UINT64_MAX;  // index full
  uint64_t off = Alloc(h, asize);
  if (off == UINT64_MAX) return UINT64_MAX;
  memcpy(s->id, id, kIdLen);
  s->off = off;
  s->size = size;
  s->state = kBuilding;
  s->probe = 1;
  h->hdr->used += asize;
  h->hdr->nobjects++;
  return h->hdr->data_off + off;
}

// Publish. Returns size or UINT64_MAX if unknown id.
uint64_t ns_seal(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s == nullptr) return UINT64_MAX;
  s->state = kSealed;
  return s->size;
}

// Lookup. Returns state (0 absent, 1 building, 2 sealed); fills
// absolute offset + logical size when sealed.
uint32_t ns_lookup(void* handle, const uint8_t* id, uint64_t* off,
                   uint64_t* size) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s == nullptr || s->state == kZombie) return 0;
  if (off) *off = h->hdr->data_off + s->off;
  if (size) *size = s->size;
  return s->state;
}

// Delete. The extent is freed immediately when unreferenced; with live
// readers the slot turns ZOMBIE (invisible to lookups) and its bytes
// are reclaimed on the last release/reap — never under a reader.
uint64_t ns_delete(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s == nullptr || s->state == kZombie) return 0;
  uint64_t asize = AlignUp(s->size ? s->size : 1);
  h->hdr->nobjects--;
  if (s->refcnt > 0) {
    s->state = kZombie;
    return 0;
  }
  FreeSlot(h, s);
  return asize;
}

// Evict: free ONLY if no reader holds a reference (the eviction path —
// plasma never evicts referenced objects). Returns freed bytes, 0 if
// absent/referenced.
uint64_t ns_evict(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s == nullptr || s->state == kZombie || s->refcnt > 0) return 0;
  uint64_t asize = AlignUp(s->size ? s->size : 1);
  h->hdr->nobjects--;
  FreeSlot(h, s);
  return asize;
}

// Acquire a read reference (sealed objects only). Returns state.
uint32_t ns_acquire(void* handle, const uint8_t* id, int32_t pid,
                    uint64_t* off, uint64_t* size) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s == nullptr || s->state != kSealed) return s ? s->state : 0;
  Reader* r = FindReader(h, pid, (uint32_t)(s - h->slots), true);
  if (r == nullptr) return 0;  // ledger full: treat as absent (copy path)
  r->count++;
  s->refcnt++;
  if (off) *off = h->hdr->data_off + s->off;
  if (size) *size = s->size;
  return kSealed;
}

// Drop one read reference.
void ns_release(void* handle, const uint8_t* id, int32_t pid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Slot* s = FindSlot(h, id, false);
  if (s == nullptr || s->refcnt == 0) return;
  Reader* r = FindReader(h, pid, (uint32_t)(s - h->slots), false);
  if (r == nullptr || r->count == 0) return;
  r->count--;
  if (r->count == 0) r->pid = 0;
  s->refcnt--;
  if (s->refcnt == 0 && s->state == kZombie) FreeSlot(h, s);
}

// Drop ALL references held by one pid (clean client shutdown).
void ns_release_all(void* handle, int32_t pid) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  for (uint32_t i = 0; i < kMaxReaders; i++) {
    Reader* r = &h->readers[i];
    if (r->pid != pid || r->count == 0) continue;
    Slot* s = &h->slots[r->slot];
    if (s->refcnt >= r->count) s->refcnt -= r->count;
    else s->refcnt = 0;
    r->pid = 0;
    r->count = 0;
    if (s->refcnt == 0 && s->state == kZombie) FreeSlot(h, s);
  }
}

// Reap references held by dead processes (node-manager heartbeat).
// Returns number of reaped ledger entries.
uint32_t ns_reap(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  uint32_t reaped = 0;
  for (uint32_t i = 0; i < kMaxReaders; i++) {
    Reader* r = &h->readers[i];
    if (r->pid == 0 || r->count == 0) continue;
    if (kill(r->pid, 0) == -1 && errno == ESRCH) {
      Slot* s = &h->slots[r->slot];
      if (s->refcnt >= r->count) s->refcnt -= r->count;
      else s->refcnt = 0;
      r->pid = 0;
      r->count = 0;
      if (s->refcnt == 0 && s->state == kZombie) FreeSlot(h, s);
      reaped++;
    }
  }
  return reaped;
}

// Test/diagnostic hook: force the EOWNERDEAD recovery path.
void ns_recover(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  RecoverAllocator(h);
}

void ns_stats(void* handle, uint64_t* used, uint64_t* capacity,
              uint32_t* nobjects) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  if (used) *used = h->hdr->used;
  if (capacity) *capacity = h->hdr->capacity;
  if (nobjects) *nobjects = h->hdr->nobjects;
}

// Enumerate sealed objects: fills out_ids (max_n * kIdLen bytes),
// out_sizes and out_refcnts (max_n entries each); returns the count
// written. Lets the node-manager authority see locally-created objects
// it was never notified about (spill/eviction candidates) — plasma's
// store-side object table walk.
uint32_t ns_list(void* handle, uint8_t* out_ids, uint64_t* out_sizes,
                 uint32_t* out_refcnts, uint32_t max_n) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Header* hdr = h->hdr;
  uint32_t n = 0;
  for (uint32_t i = 0; i < hdr->nslots && n < max_n; i++) {
    Slot* s = &h->slots[i];
    if (s->state != kSealed) continue;
    memcpy(out_ids + static_cast<size_t>(n) * kIdLen, s->id, kIdLen);
    out_sizes[n] = s->size;
    out_refcnts[n] = s->refcnt;
    n++;
  }
  return n;
}

// Base pointer of the mapping (for ctypes buffer construction).
uint8_t* ns_base(void* handle) {
  return static_cast<Handle*>(handle)->base;
}

// Largest contiguous allocatable run (freelist max + bump tail).
uint64_t ns_largest_free(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Header* hdr = h->hdr;
  uint64_t best = hdr->capacity > hdr->bump
      ? hdr->capacity - hdr->bump : 0;
  for (uint32_t i = 0; i < hdr->nfree; i++) {
    if (h->freelist[i].size > best) best = h->freelist[i].size;
  }
  return best;
}

// Defragment: slide every MOVABLE extent (sealed, zero readers — an
// acquire takes the same lock and pins via refcnt, so movability is
// race-free) toward low addresses, packing around pinned extents
// (building / reader-held / zombie), then rebuild the freelist from
// the remaining gaps. This is what plasma gets from dlmalloc's
// boundary-tag coalescing plus eviction; a pinned-scatter arena
// otherwise fragments until no large extent fits even at low
// utilization (observed: 17 MB create failing with 48 MB of 192 MB
// held). Returns the largest contiguous free run afterwards.
uint64_t ns_compact(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  Locker lock(h);
  Header* hdr = h->hdr;
  // live slots in address order
  struct Ent { Slot* s; uint64_t off; uint64_t asize; bool movable; };
  std::vector<Ent> live;
  live.reserve(hdr->nobjects);
  for (uint32_t i = 0; i < hdr->nslots; i++) {
    Slot* s = &h->slots[i];
    if (s->state == kFree) continue;
    Ent e;
    e.s = s;
    e.off = s->off;
    e.asize = AlignUp(s->size ? s->size : 1);
    e.movable = (s->state == kSealed && s->refcnt == 0);
    live.push_back(e);
  }
  std::sort(live.begin(), live.end(),
            [](const Ent& a, const Ent& b) { return a.off < b.off; });
  uint8_t* data = h->base + hdr->data_off;
  // extents are disjoint and processed in address order, so cursor
  // (end of the previous packed/pinned extent) never exceeds the next
  // extent's offset
  uint64_t cursor = 0;
  for (auto& e : live) {
    if (!e.movable) {
      // pinned: the gap [cursor, e.off) stays free; packing resumes
      // after it
      cursor = e.off + e.asize;
      continue;
    }
    if (e.off > cursor) {
      memmove(data + cursor, data + e.off, e.asize);
      e.s->off = cursor;
      e.off = cursor;
    }
    cursor = e.off + e.asize;
  }
  // rebuild freelist + bump from the (possibly moved) extents
  std::sort(live.begin(), live.end(),
            [](const Ent& a, const Ent& b) { return a.off < b.off; });
  uint64_t scan = 0;
  uint32_t nfree = 0;
  for (auto& e : live) {
    if (e.off > scan && nfree < kMaxFree) {
      h->freelist[nfree].off = scan;
      h->freelist[nfree].size = e.off - scan;
      nfree++;
    }
    uint64_t end = e.off + e.asize;
    if (end > scan) scan = end;
  }
  hdr->bump = scan;
  hdr->nfree = nfree;
  uint64_t best = hdr->capacity > scan ? hdr->capacity - scan : 0;
  for (uint32_t i = 0; i < nfree; i++) {
    if (h->freelist[i].size > best) best = h->freelist[i].size;
  }
  return best;
}

uint64_t ns_total_size(void* handle) {
  return static_cast<Handle*>(handle)->mapped;
}

void ns_close(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->base, h->mapped);
  close(h->fd);
  delete h;
}

}  // extern "C"
