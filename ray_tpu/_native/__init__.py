"""ctypes loader for the native store (builds on first use).

The C++ extension is optional: if g++ (or a prebuilt
``libnativestore.so``) is unavailable the Python mmap store is used.
Set ``RAY_TPU_NATIVE_STORE=0`` to force the fallback.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_PATH = os.path.join(_HERE, "store.cpp")


def _lib_path() -> str:
    """Build artifact keyed by a source hash: editing store.cpp naturally
    invalidates the old binary (mtime comparison breaks under git checkout,
    which restores old mtimes), and no binary is ever committed."""
    with open(_SRC_PATH, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_HERE, f"libnativestore-{digest}.so")


_LIB_PATH = _lib_path()

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # Sanitizer-instrumented builds live in tests/core/test_store_sanitize.py
    # (a standalone stress binary over the same TU) — the loader builds
    # the production library only.
    # pid-unique temp output: concurrent builders (several node
    # managers starting at once) must not clobber each other mid-write.
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, _SRC_PATH, "-lpthread"]
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if out.returncode != 0:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        return False
    os.replace(tmp, _LIB_PATH)
    # reap binaries for older source revisions (processes that still have
    # one mapped keep it alive via the inode; the name can go)
    cur = os.path.basename(_LIB_PATH)
    for name in os.listdir(_HERE):
        if name.startswith("libnativestore") and name.endswith(".so") \
                and name != cur:
            try:
                os.unlink(os.path.join(_HERE, name))
            except OSError:
                pass
    return True


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
            return None
        if not os.path.exists(_LIB_PATH):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.ns_create.restype = ctypes.c_void_p
        lib.ns_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint32]
        lib.ns_open.restype = ctypes.c_void_p
        lib.ns_open.argtypes = [ctypes.c_char_p]
        lib.ns_alloc.restype = ctypes.c_uint64
        lib.ns_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64]
        lib.ns_seal.restype = ctypes.c_uint64
        lib.ns_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ns_lookup.restype = ctypes.c_uint32
        lib.ns_lookup.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.ns_delete.restype = ctypes.c_uint64
        lib.ns_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ns_evict.restype = ctypes.c_uint64
        lib.ns_evict.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ns_acquire.restype = ctypes.c_uint32
        lib.ns_acquire.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.ns_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int32]
        lib.ns_release_all.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ns_reap.restype = ctypes.c_uint32
        lib.ns_reap.argtypes = [ctypes.c_void_p]
        lib.ns_recover.argtypes = [ctypes.c_void_p]
        lib.ns_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.ns_list.restype = ctypes.c_uint32
        lib.ns_list.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32]
        lib.ns_base.restype = ctypes.c_void_p
        lib.ns_largest_free.restype = ctypes.c_uint64
        lib.ns_largest_free.argtypes = [ctypes.c_void_p]
        lib.ns_compact.restype = ctypes.c_uint64
        lib.ns_compact.argtypes = [ctypes.c_void_p]
        lib.ns_base.argtypes = [ctypes.c_void_p]
        lib.ns_total_size.restype = ctypes.c_uint64
        lib.ns_total_size.argtypes = [ctypes.c_void_p]
        lib.ns_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib
