// Multithreaded stress driver for the native store, built with
// -fsanitize=thread / -fsanitize=address by tests/core/test_store_sanitize.py
// (reference: the C++ runtime ships TSAN/ASAN CI configs — bazel
// --config=tsan/asan over the raylet/plasma cc_tests).
//
// Single translation unit: includes store.cpp directly so the stress
// binary links the sanitizer runtime into every store function.
//
// Exercises the full concurrent surface: allocation, seal, lookup,
// acquire/release readers, delete-under-reader (zombie path), evict,
// reap, and stats, from N writer threads + N reader threads sharing one
// segment. Exits 0 iff all invariants held (sanitizer findings abort
// the process by themselves).

#include "store.cpp"

#include <atomic>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

std::atomic<uint64_t> g_errors{0};

void FillId(uint8_t* id, int writer, int i) {
  std::memset(id, 0, 28);
  std::memcpy(id, &writer, sizeof(writer));
  std::memcpy(id + 8, &i, sizeof(i));
}

void WriterLoop(void* h, int writer, int iters) {
  uint8_t id[28];
  for (int i = 0; i < iters; i++) {
    FillId(id, writer, i);
    uint64_t size = 64 + (i % 17) * 64;
    uint64_t off = ns_alloc(h, id, size);
    if (off == ~0ULL || off == ~0ULL - 1) continue;  // full / exists
    ns_seal(h, id);
    if (i % 3 == 0) {
      ns_delete(h, id);   // may zombie under a racing reader
    } else if (i % 3 == 1) {
      ns_evict(h, id);    // refuses under readers
    }
    if (i % 64 == 0) {
      uint64_t used, cap;
      uint32_t n;
      ns_stats(h, &used, &cap, &n);
      if (used > cap * 4) g_errors++;
    }
  }
}

void ReaderLoop(void* h, int target_writer, int iters, int pid) {
  uint8_t id[28];
  for (int i = 0; i < iters; i++) {
    FillId(id, target_writer, i % 97);
    uint64_t off = 0, size = 0;
    uint32_t st = ns_acquire(h, id, pid, &off, &size);
    if (st == 2) {
      if (size == 0) g_errors++;
      ns_release(h, id, pid);
    }
    ns_lookup(h, id, &off, &size);
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* path = argc > 1 ? argv[1] : "/dev/shm/_store_stress.seg";
  int iters = argc > 2 ? std::atoi(argv[2]) : 4000;
  std::remove(path);
  void* h = ns_create(path, 256ull << 20, 4096);
  if (h == nullptr) {
    std::fprintf(stderr, "ns_create failed\n");
    return 2;
  }
  const int kWriters = 4, kReaders = 4;
  std::vector<std::thread> ts;
  for (int w = 0; w < kWriters; w++)
    ts.emplace_back(WriterLoop, h, w, iters);
  for (int r = 0; r < kReaders; r++)
    ts.emplace_back(ReaderLoop, h, r % kWriters, iters, 1000 + r);
  for (auto& t : ts) t.join();
  // crash-cleanup path: pretend every reader pid died
  ns_reap(h);
  uint64_t used, cap;
  uint32_t n;
  ns_stats(h, &used, &cap, &n);
  ns_close(h);
  std::remove(path);
  if (g_errors.load() != 0) {
    std::fprintf(stderr, "invariant violations: %llu\n",
                 (unsigned long long)g_errors.load());
    return 1;
  }
  std::printf("stress ok: %u objects resident, %llu bytes\n", n,
              (unsigned long long)used);
  return 0;
}
