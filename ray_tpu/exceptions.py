"""Error model: exceptions stored as task results and re-raised at ``get``.

Equivalent of the reference's ``python/ray/exceptions.py`` (RayTaskError
:46, RayActorError, ObjectLostError, TaskCancelledError, OutOfMemoryError).
A failed task's result object *is* its exception; ``ray_tpu.get`` re-raises
it on the caller with the remote traceback attached.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution.

    Stored as the task's return object; re-raised at ``get`` with the remote
    traceback string (reference: RayTaskError.as_instanceof_cause).
    """

    def __init__(self, function_name: str, traceback_str: str,
                 cause: Optional[BaseException] = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"Task {function_name} failed.\nRemote traceback:\n{traceback_str}"
        )

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(function_name, tb, cause=exc)

    def __reduce__(self):
        # cause may not be picklable; degrade to its repr
        cause = self.cause
        try:
            import pickle
            pickle.dumps(cause)
        except Exception:
            cause = None
        return (TaskError, (self.function_name, self.traceback_str, cause))


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor is dead; pending and future calls fail with this.

    Reference: RayActorError / ActorDiedError (python/ray/exceptions.py),
    produced by GcsActorManager death notifications.
    """

    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id, self.reason))


class ActorUnavailableError(ActorError):
    """Actor temporarily unreachable (e.g. restarting).

    Raised for calls that race an actor restart and are not retriable
    (``max_task_retries=0``). Unlike :class:`ActorDiedError` the actor
    may become ALIVE again — callers holding the handle can retry;
    retriable calls are instead queued transparently until the actor
    re-resolves (reference: python/ray/exceptions.py
    ActorUnavailableError semantics).
    """

    def __init__(self, actor_id=None, reason: str = "actor is restarting"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} unavailable: {reason}")

    def __reduce__(self):
        return (ActorUnavailableError, (self.actor_id, self.reason))


class ObjectLostError(RayTpuError):
    """Object's value was lost and could not be reconstructed via lineage.

    Reference: python/ray/exceptions.py ObjectLostError and the recovery
    path in src/ray/core_worker/object_recovery_manager.h:90.
    """

    def __init__(self, object_ref=None, reason: str = "all copies lost"):
        self.object_ref = object_ref
        self.reason = reason
        super().__init__(f"Object {object_ref} lost: {reason}")

    def __reduce__(self):
        return (ObjectLostError, (self.object_ref, self.reason))


class OwnerDiedError(ObjectLostError):
    def __init__(self, object_ref=None):
        super(ObjectLostError, self).__init__(
            f"Object {object_ref} unrecoverable: owner died")
        self.object_ref = object_ref


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id,))


class OutOfMemoryError(RayTpuError):
    """Raised when the node memory monitor kills a task (reference:
    src/ray/common/memory_monitor.h + worker_killing_policy.h)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(..., timeout=)`` expired before the object was ready."""


class RpcTimeoutError(RayTpuError, TimeoutError):
    """A control-plane request/reply RPC timed out.

    Carries the wire message type and the elapsed wait so a timeout is
    attributable from the exception alone (reference: gRPC deadline
    exceeded statuses carry the method name). Subclasses TimeoutError so
    pre-existing catch sites keep working.
    """

    def __init__(self, mtype: Optional[bytes] = None,
                 elapsed_s: Optional[float] = None):
        self.mtype = mtype
        self.elapsed_s = elapsed_s
        what = mtype.decode("ascii", "replace") if mtype else "?"
        took = f" after {elapsed_s:.1f}s" if elapsed_s is not None else ""
        super().__init__(
            f"control-plane RPC {what} timed out{took}")

    def __reduce__(self):
        return (RpcTimeoutError, (self.mtype, self.elapsed_s))


class DeliveryFailedError(RayTpuError):
    """The reliable-delivery layer gave up on a one-way control message:
    it was retransmitted to the attempt cap without an ack and the peer
    was never declared dead. Surfaced through the transport's ``on_fail``
    hook / ``failures`` list rather than raised at a call site — one-way
    messages have no waiting caller.
    """

    def __init__(self, mtype: Optional[bytes] = None, target=None,
                 attempts: int = 0, elapsed_s: float = 0.0):
        self.mtype = mtype
        self.target = target
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        what = mtype.decode("ascii", "replace") if mtype else "?"
        peer = target.hex()[:12] if isinstance(target, bytes) else \
            ("controller" if target is None else repr(target))
        super().__init__(
            f"delivery of {what} to {peer} failed after {attempts} "
            f"attempts over {elapsed_s:.1f}s (no ack, no death notice)")

    def __reduce__(self):
        return (DeliveryFailedError,
                (self.mtype, self.target, self.attempts, self.elapsed_s))


class StreamCancelledError(RayTpuError):
    """An ``ObjectRefGenerator`` was iterated after ``close()``/``cancel()``.

    Early consumer termination cancels the producer task and drops the
    stream's buffered item refs; further iteration is a caller bug and
    surfaces as this typed error rather than a hang on items that will
    never arrive.
    """

    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"stream of task {task_id} was cancelled")

    def __reduce__(self):
        return (StreamCancelledError, (self.task_id,))


class AdmissionRejectedError(RayTpuError):
    """SLO-aware admission shed this request at the router before it
    reached a replica queue (``serve/admission.py``): the tenant is
    over its token budget, or the serve fleet is overloaded and the
    request's priority class is below the shed line. Retry later, with
    a higher priority class, or under a different tenant budget — the
    HTTP proxy maps this to 429 Too Many Requests.
    """

    def __init__(self, tenant: str = "default",
                 priority: str = "normal", reason: str = "overload",
                 detail: str = "", request_id: str = ""):
        self.tenant = tenant
        self.priority = priority
        self.reason = reason
        self.detail = detail
        # trace identity of the shed request, when the router minted
        # one — lets 429 bodies and ARBITER_REJECT events be joined
        # against the request-trace store's SHED waterfall
        self.request_id = request_id
        super().__init__(
            f"request shed at admission ({reason}): tenant "
            f"{tenant!r}, priority {priority!r}"
            + (f" — {detail}" if detail else "")
            + (f" [request_id={request_id}]" if request_id else ""))

    def __reduce__(self):
        return (AdmissionRejectedError,
                (self.tenant, self.priority, self.reason, self.detail,
                 self.request_id))


class ObjectStoreFullError(RayTpuError):
    """Shared-memory store is full and eviction/spill could not make room."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up a task/actor runtime environment."""


class PendingCallsLimitExceeded(RayTpuError):
    """Too many in-flight calls to an actor (max_pending_calls)."""
