"""Runtime context (reference: ``python/ray/runtime_context.py``)."""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.global_state import global_worker


class RuntimeContext:
    def __init__(self, worker):
        self._w = worker

    def get_job_id(self) -> str:
        return self._w.job_id.hex()

    def get_node_id(self) -> str:
        return self._w.node_id.hex()

    def get_worker_id(self) -> str:
        return self._w.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        return self._w.current_task_id.hex()

    def get_actor_id(self) -> Optional[str]:
        aid = getattr(self._w, "_current_actor_id", None)
        return aid.hex() if aid else None

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_actor_handle(self):
        from ray_tpu.actor import ActorHandle
        aid = getattr(self._w, "_current_actor_id", None)
        if aid is None:
            raise RuntimeError("not running inside an actor")
        return ActorHandle(aid)


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(global_worker())
