"""IMPALA: importance-weighted actor-learner architecture.

Reference: ``rllib/algorithms/impala/impala.py`` (decoupled sampling +
learning with V-trace off-policy correction; torch loss in
``impala/torch/impala_torch_learner.py``). TPU-native design: the
whole V-trace recursion runs inside the jitted loss as a reversed
``lax.scan`` over the time axis — no host-side bootstrapping pass — and
the policy/value/entropy terms fuse into the same XLA program as the
optimizer update. Weights broadcast to runners every
``broadcast_interval`` iterations, so sample batches are mildly stale
and V-trace's clipped importance ratios (rho/c) do the correction.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig


def vtrace_returns(target_logp, behavior_logp, rewards, values,
                   bootstrap_value, dones, gamma: float,
                   rho_clip: float, c_clip: float):
    """V-trace targets vs_t and policy-gradient advantages, [T, B].

    vs_t = V(x_t) + sum_k gamma^k (prod c) delta_k  — computed as the
    standard backward recursion under ``lax.scan`` (jit-friendly, no
    Python loop over T).
    """
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_clip)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_clip)
    discount = gamma * (1.0 - dones)
    values_tp1 = jnp.concatenate(
        [values[1:], bootstrap_value[None, :]], axis=0)
    deltas = rho * (rewards + discount * values_tp1 - values)

    def scan_fn(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, discount, c), reverse=True)
    vs = values + vs_minus_v
    vs_tp1 = jnp.concatenate([vs[1:], bootstrap_value[None, :]], axis=0)
    pg_adv = rho * (rewards + discount * vs_tp1 - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def impala_loss(fwd_out: Dict[str, jnp.ndarray],
                batch: Dict[str, jnp.ndarray], *,
                rollout_len: int = 40,
                gamma: float = 0.99,
                vf_loss_coeff: float = 0.5,
                entropy_coeff: float = 0.01,
                rho_clip: float = 1.0,
                c_clip: float = 1.0):
    T = rollout_len
    logits = fwd_out["action_logits"]          # [T*B, A] time-major
    values_flat = fwd_out["vf_preds"]          # [T*B]
    B = logits.shape[0] // T
    A = logits.shape[-1]

    logp_all = jax.nn.log_softmax(logits)
    logp_act = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]

    tb = lambda x: x.reshape(T, B)  # noqa: E731
    target_logp = tb(logp_act)
    behavior_logp = tb(batch["behavior_logp"])
    values = tb(values_flat)
    rewards = tb(batch["rewards"])
    dones = tb(batch["dones"])
    bootstrap = batch["bootstrap_value"]       # [B]

    vs, pg_adv = vtrace_returns(
        target_logp, behavior_logp, rewards, values, bootstrap, dones,
        gamma, rho_clip, c_clip)

    policy_loss = -jnp.mean(target_logp * pg_adv)
    vf_loss = 0.5 * jnp.mean(jnp.square(vs - values))
    entropy = -jnp.mean(jnp.sum(
        jnp.exp(logp_all) * logp_all, axis=-1))
    total = policy_loss + vf_loss_coeff * vf_loss \
        - entropy_coeff * entropy
    metrics = {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "mean_rho": jnp.mean(jnp.exp(target_logp - behavior_logp)),
    }
    return total, metrics


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.rollout_len: int = 40
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.vtrace_rho_clip: float = 1.0
        self.vtrace_c_clip: float = 1.0
        #: sync weights to runners every N iterations (1 = on-policy-ish)
        self.broadcast_interval: int = 1
        self.lr = 5e-4
        self.num_epochs = 1
        self.minibatch_size = None


class IMPALA(Algorithm):
    config_cls = IMPALAConfig

    def loss_fn(self):
        return impala_loss

    def loss_config(self) -> Dict[str, Any]:
        c = self.config
        return {
            "rollout_len": c.rollout_len,
            "gamma": c.gamma,
            "vf_loss_coeff": c.vf_loss_coeff,
            "entropy_coeff": c.entropy_coeff,
            "rho_clip": c.vtrace_rho_clip,
            "c_clip": c.vtrace_c_clip,
        }

    def setup(self, cfg_dict: Dict) -> None:
        super().setup(cfg_dict)
        self._iter_count = 0

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        T = cfg.rollout_len
        futs = [r.sample_segments.remote(T) for r in self.env_runners]
        batches = ray_tpu.get(futs)
        # concat along the ENV axis (axis=1 of [T, B_i, ...]), then
        # flatten time-major so index t*B+b matches the loss's reshape
        seg = {k: np.concatenate([b[k] for b in batches], axis=1)
               for k in batches[0] if k != "bootstrap_value"}
        B = seg["actions"].shape[1]
        flat = {k: v.reshape((T * B,) + v.shape[2:])
                for k, v in seg.items()}
        flat["bootstrap_value"] = np.concatenate(
            [b["bootstrap_value"] for b in batches], axis=0)
        self._timesteps += T * B

        metrics = self.learner_group.update_ordered(flat)
        self._iter_count += 1
        if self._iter_count % max(1, cfg.broadcast_interval) == 0:
            self._sync_weights()

        returns = []
        for r in ray_tpu.get(
                [r.episode_returns.remote() for r in self.env_runners]):
            returns.extend(r)
        self._return_window.extend(returns)
        self._return_window = self._return_window[-100:]
        mean_return = (float(np.mean(self._return_window))
                       if self._return_window else float("nan"))
        return {
            "episode_return_mean": mean_return,
            "episode_reward_mean": mean_return,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "learner": metrics,
        }
