"""Rollout→train streaming dataflow (Podracer-style decoupled
actor/learner, MindSpeed-RL-style distributed rollout feed).

Reference points: arXiv:2104.06272 (Podracer/sebulba: decoupled
rollout producers feeding a learner through a queue) and
arXiv:2507.19017 (MindSpeed RL: rollout workers stream samples into
the trainer's data plane instead of epoch barriers).

``rollout_stream`` is a **generator task** (``num_returns=
"streaming"``), not an actor method: it is deterministic in its
arguments (env construction, module init and action sampling are all
seeded), so a mid-epoch SIGKILL of a runner's worker lineage-replays
the stream prefix on a fresh worker and the owner's per-index dedup
delivers every block to the consumer exactly once — the learner never
sees a duplicate or a hole.

``RolloutBlockStream`` is the fan-in consumer edge: ``wait_any``
surfaces whichever runner has a block buffered (one straggler never
stalls the learner), blocks re-chunk into fixed minibatches via
``iter_batches`` (numpy twin of ``data.iterator.
iter_batches_over_blocks``), and the time the consumer spends blocked
with no block ready is measured as the rollout→train *bubble* —
the number ``bench.py --data`` reports streaming vs epoch-barriered.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.rl_module import RLModuleSpec


class RandomEnv:
    """Gym-free env for benches/tests (no gymnasium dependency):
    seeded random-walk observations, +1 reward per step, fixed-length
    episodes. Speaks the 5-tuple gymnasium step API the EnvRunner
    consumes."""

    class _Space:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    def __init__(self, obs_dim: int = 8, n_actions: int = 4,
                 episode_len: int = 50, seed: int = 0):
        self.obs_dim = obs_dim
        self.n_actions = n_actions
        self.episode_len = episode_len
        self._rng = np.random.default_rng(seed)
        self._t = 0
        # minimal gym-shaped spaces so Algorithm.setup's space probe
        # (spec_for_spaces) works without gymnasium
        self.observation_space = self._Space(shape=(obs_dim,))
        self.action_space = self._Space(n=n_actions)

    def close(self) -> None:
        pass

    def _obs(self) -> np.ndarray:
        return self._rng.standard_normal(self.obs_dim).astype(np.float32)

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        self._t += 1
        terminated = self._t >= self.episode_len
        if terminated:
            self._t = 0
        return self._obs(), 1.0, terminated, False, {}


def block_uid(worker_index: int, block: int) -> int:
    """Stable per-(runner, block) id carried as a row column so
    exactly-once delivery is assertable end to end."""
    return worker_index * 1_000_000 + block


def rollout_stream(env_creator: Callable[[], Any],
                   module_spec: RLModuleSpec, weights,
                   num_blocks: int, steps_per_block: int,
                   num_envs: int = 1, gamma: float = 0.99,
                   lambda_: float = 0.95, seed: int = 0,
                   worker_index: int = 0,
                   fault: Optional[Dict[str, Any]] = None):
    """Generator-task body: build a (deterministically seeded)
    EnvRunner in-process and yield ``num_blocks`` rollout blocks of
    ``steps_per_block`` env steps each. Each item is ``(batch, info)``:
    the flat GAE'd sample batch (plus a ``block_uid`` row column) and
    a small info dict (episode returns, ids).

    ``fault={"die_at_block": i, "marker": path}`` is the chaos hook
    used by tests and the bench's kill leg: the first execution
    SIGKILLs its own worker right before yielding block ``i`` (and
    drops a marker file so the lineage replay runs through)."""
    from ray_tpu.rllib.env_runner import EnvRunner
    runner = EnvRunner(env_creator, module_spec, num_envs=num_envs,
                       gamma=gamma, lambda_=lambda_, seed=seed,
                       worker_index=worker_index)
    runner.set_weights(weights)
    blocks = runner.sample_blocks(num_blocks, steps_per_block)
    for b, batch in enumerate(blocks):
        if fault and b == fault.get("die_at_block"):
            import os
            marker = fault.get("marker")
            if marker and not os.path.exists(marker):
                open(marker, "w").close()
                os.kill(os.getpid(), __import__("signal").SIGKILL)
        uid = block_uid(worker_index, b)
        batch["block_uid"] = np.full(len(batch["obs"]), uid, np.int64)
        info = {"worker_index": worker_index, "block": b, "uid": uid,
                "episode_returns": runner.episode_returns()}
        yield batch, info


_rollout_stream_remote = None


def _remote_rollout_stream():
    global _rollout_stream_remote
    if _rollout_stream_remote is None:
        _rollout_stream_remote = ray_tpu.remote(
            num_cpus=1, num_returns="streaming")(rollout_stream)
    return _rollout_stream_remote


def make_rollout_streams(env_creator, module_spec, weights,
                         n_runners: int, num_blocks: int,
                         steps_per_block: int, *, num_envs: int = 1,
                         gamma: float = 0.99, lambda_: float = 0.95,
                         seed: int = 0, backpressure: int = 4,
                         faults: Optional[Dict[int, Dict]] = None
                         ) -> List[Any]:
    """Launch N rollout generator tasks; returns their
    ``ObjectRefGenerator``s. ``weights`` may be a value or an
    ``ObjectRef`` (put once, resolved at each runner). ``faults`` maps
    worker_index → fault dict (see ``rollout_stream``)."""
    fn = _remote_rollout_stream()
    return [
        fn.options(generator_backpressure_num_objects=backpressure)
        .remote(env_creator, module_spec, weights, num_blocks,
                steps_per_block, num_envs, gamma, lambda_,
                seed, i, (faults or {}).get(i))
        for i in range(n_runners)]


def _concat_batches(batches: List[Dict[str, np.ndarray]]
                    ) -> Dict[str, np.ndarray]:
    return {k: np.concatenate([b[k] for b in batches])
            for k in batches[0]}


def _nrows(batch: Dict[str, np.ndarray]) -> int:
    """Row count of a sample batch: every column shares the leading
    axis, so any column works — env batches key their rows by ``obs``,
    RLHF trajectory batches by ``tokens``."""
    if "obs" in batch:
        return len(batch["obs"])
    return len(next(iter(batch.values())))


class RolloutBlockStream:
    """Fan-in over N rollout streams: completion-order block iteration
    via ``wait_any``, minibatch re-chunking, and consumer-idle (bubble)
    accounting."""

    def __init__(self, generators: List[Any], collect: bool = False):
        self._gens = list(generators)
        self._collect = collect
        self.blocks: List[Dict[str, np.ndarray]] = []
        self.infos: List[Dict[str, Any]] = []
        self._wait_s = 0.0
        self._wall_t0: Optional[float] = None
        self._wall_s = 0.0
        self._rows = 0

    # ------------------------------------------------------------ blocks
    def iter_blocks(self, timeout: float = 600.0
                    ) -> Iterator[Tuple[Dict[str, np.ndarray],
                                        Dict[str, Any]]]:
        """Yield ``(batch, info)`` from whichever runner has one ready
        (completion order — a straggling runner never stalls the
        learner). Time blocked with nothing ready accrues to the
        measured rollout→train bubble."""
        from ray_tpu.core.streaming import wait_any
        if self._wall_t0 is None:
            self._wall_t0 = time.perf_counter()
        pending = list(self._gens)
        deadline = time.monotonic() + timeout
        while pending:
            t0 = time.perf_counter()
            ready, _ = wait_any(pending, timeout=30.0)
            self._wait_s += time.perf_counter() - t0
            if not ready:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        "no rollout block arrived before the deadline")
                continue
            for g in ready:
                try:
                    ref = g.next_ref(timeout=0.5)
                except StopIteration:
                    continue
                except Exception:
                    if g.is_finished():
                        raise
                    continue
                t0 = time.perf_counter()
                batch, info = ray_tpu.get(ref)
                self._wait_s += time.perf_counter() - t0
                self._rows += _nrows(batch)
                if self._collect:
                    self.blocks.append(batch)
                self.infos.append(info)
                yield batch, info
            pending = [g for g in pending if not g.is_finished()]
        self._wall_s = time.perf_counter() - self._wall_t0

    # ----------------------------------------------------------- batches
    def iter_batches(self, batch_size: Optional[int] = None,
                     drop_last: bool = False
                     ) -> Iterator[Dict[str, np.ndarray]]:
        """The learner's consume edge: re-chunk the arriving blocks
        into fixed ``batch_size`` minibatches (numpy twin of the data
        layer's ``iter_batches_over_blocks``)."""
        carry: List[Dict[str, np.ndarray]] = []
        carry_rows = 0
        for batch, _info in self.iter_blocks():
            if batch_size is None:
                yield batch
                continue
            carry.append(batch)
            carry_rows += _nrows(batch)
            while carry_rows >= batch_size:
                merged = _concat_batches(carry)
                n = _nrows(merged)
                yield {k: v[:batch_size] for k, v in merged.items()}
                rest = {k: v[batch_size:] for k, v in merged.items()}
                carry = [rest] if n > batch_size else []
                carry_rows = n - batch_size
        if batch_size is not None and carry_rows and not drop_last:
            yield _concat_batches(carry)

    # ------------------------------------------------------------- stats
    def full_batch(self) -> Dict[str, np.ndarray]:
        """All collected blocks as one batch (requires
        ``collect=True``); feeds the shuffled epochs after the
        streamed first pass."""
        if not self.blocks:
            raise ValueError("no blocks collected "
                             "(construct with collect=True)")
        return _concat_batches(self.blocks)

    def episode_returns(self) -> List[float]:
        out: List[float] = []
        for info in self.infos:
            out.extend(info.get("episode_returns", []))
        return out

    def delivered_uids(self) -> List[int]:
        return [info["uid"] for info in self.infos]

    def stats(self) -> Dict[str, float]:
        wall = self._wall_s or (
            time.perf_counter() - self._wall_t0
            if self._wall_t0 is not None else 0.0)
        return {
            "rows": self._rows,
            "blocks": len(self.infos),
            "wait_s": round(self._wait_s, 4),
            "wall_s": round(wall, 4),
            # fraction of the consume wall the learner sat idle
            # waiting on rollouts
            "bubble": round(self._wait_s / wall, 4) if wall > 0 else 0.0,
        }

    def close(self) -> None:
        for g in self._gens:
            try:
                g.close()
            except Exception:
                pass
