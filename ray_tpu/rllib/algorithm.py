"""Algorithm: the RL training driver, a Tune Trainable.

Reference: ``rllib/algorithms/algorithm.py:202`` (``step`` :810,
``training_step`` :1633): sample in parallel from env-runner actors,
update via the LearnerGroup, sync weights back, report
episode-return metrics. Checkpointing via the Trainable protocol, so
``Tuner(PPO, ...)`` works unchanged.
"""

from __future__ import annotations

import pickle
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.rl_module import RLModuleSpec
from ray_tpu.tune.trainable import Trainable


def _resolve_env_creator(env, env_config) -> Callable[[], Any]:
    if callable(env) and not isinstance(env, str):
        return lambda: env(env_config)
    if isinstance(env, str):
        def make():
            import gymnasium as gym
            return gym.make(env, **env_config)
        return make
    raise ValueError(f"Cannot resolve env: {env!r}")


def spec_for_spaces(obs_space, act_space, hiddens,
                    dist_for_box: str = "gaussian") -> RLModuleSpec:
    """Build the module spec from gymnasium spaces: Discrete ->
    categorical head, Box -> diagonal-Gaussian head (reference: the
    model catalog's action-distribution selection,
    ``rllib/models/catalog.py`` get_action_dist)."""
    obs_dim = int(np.prod(obs_space.shape))
    if hasattr(act_space, "n"):  # Discrete
        return RLModuleSpec(observation_dim=obs_dim,
                            num_actions=int(act_space.n),
                            hiddens=tuple(hiddens))
    if hasattr(act_space, "low"):  # Box
        return RLModuleSpec(
            observation_dim=obs_dim,
            action_dim=int(np.prod(act_space.shape)),
            dist=dist_for_box,
            action_low=tuple(np.asarray(act_space.low,
                                        np.float32).ravel()),
            action_high=tuple(np.asarray(act_space.high,
                                         np.float32).ravel()),
            hiddens=tuple(hiddens))
    raise ValueError(f"Unsupported action space: {act_space!r}")


class Algorithm(Trainable):
    """Subclasses define ``loss_fn`` + ``loss_config`` via config."""

    config_cls = AlgorithmConfig
    #: whether this algorithm's loss handles Box-space (Gaussian)
    #: policies — PPO and SAC do; discrete-only losses fail fast at
    #: build time instead of a KeyError inside the first jitted update
    supports_continuous = False

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls.config_cls(algo_class=cls)

    def __init__(self, config: Optional[AlgorithmConfig] = None, **kw):
        if config is None:
            config = self.get_default_config()
        if isinstance(config, dict):
            base = self.get_default_config()
            for k, v in config.items():
                setattr(base, k, v)
            config = base
        self._algo_config = config
        super().__init__(config.to_dict())

    # -- Trainable protocol -------------------------------------------
    def setup(self, _cfg: Dict) -> None:
        # Trainable.__init__ rebound self.config to the plain dict;
        # expose the AlgorithmConfig object (reference behavior).
        cfg = self.config = self._algo_config
        env_creator = self._env_creator = _resolve_env_creator(
            cfg.env, cfg.env_config)
        probe = env_creator()
        self.module_spec = spec_for_spaces(
            probe.observation_space, probe.action_space,
            cfg.model.get("fcnet_hiddens", (64, 64)))
        if self.module_spec.is_continuous and not self.supports_continuous:
            raise ValueError(
                f"{type(self).__name__} supports Discrete action spaces "
                f"only; use PPO or SAC for Box spaces")
        try:
            probe.close()
        except Exception:
            pass

        spec = self.module_spec
        loss_fn = self.loss_fn()
        loss_config = self.loss_config()
        lr, clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def make_learner() -> Learner:
            return Learner(spec, loss_fn, learning_rate=lr,
                           grad_clip=clip, seed=seed,
                           loss_config=loss_config)

        self.learner_group = LearnerGroup(
            make_learner, num_learners=cfg.num_learners, seed=cfg.seed)
        self._inference_module = spec.build()
        self._cached_weights = None

        n_runners = max(1, cfg.num_env_runners)
        if getattr(cfg, "streaming_rollouts", False):
            # Rollout producers are per-step generator TASKS
            # (rollout_stream.py) — deterministic, lineage-replayable.
            # No long-lived runner actors to keep in sync.
            self.env_runners = []
        else:
            runner_cls = ray_tpu.remote(num_cpus=1)(EnvRunner)
            self.env_runners = [
                runner_cls.remote(env_creator, spec,
                                  cfg.num_envs_per_env_runner,
                                  cfg.gamma,
                                  getattr(cfg, "lambda_", 0.95),
                                  cfg.seed, i)
                for i in range(n_runners)]
        self._sync_weights()
        self._timesteps = 0
        self._iterations = 0
        self._return_window: List[float] = []

    # Subclass hooks ---------------------------------------------------
    def loss_fn(self) -> Callable:
        raise NotImplementedError

    def loss_config(self) -> Dict[str, Any]:
        return {}

    # ------------------------------------------------------------------
    def _sync_weights(self) -> None:
        self._cached_weights = self.learner_group.get_weights()
        w_ref = ray_tpu.put(self._cached_weights)
        ray_tpu.get([r.set_weights.remote(w_ref)
                     for r in self.env_runners])

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        if getattr(cfg, "streaming_rollouts", False):
            return self._step_streaming()
        per_runner = max(1, cfg.train_batch_size
                         // (len(self.env_runners)
                             * cfg.num_envs_per_env_runner))
        batches = ray_tpu.get(
            [r.sample.remote(per_runner) for r in self.env_runners])
        batch = {k: np.concatenate([b[k] for b in batches])
                 for k in batches[0]}
        self._timesteps += len(batch["obs"])

        metrics = self.learner_group.update_from_batch(
            batch, minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs)
        self._sync_weights()

        returns: List[float] = []
        for r in ray_tpu.get(
                [r.episode_returns.remote() for r in self.env_runners]):
            returns.extend(r)
        self._return_window.extend(returns)
        self._return_window = self._return_window[-100:]
        mean_return = (float(np.mean(self._return_window))
                       if self._return_window else float("nan"))
        return {
            "episode_return_mean": mean_return,
            # legacy alias used by older tuned examples
            "episode_reward_mean": mean_return,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "learner": metrics,
        }

    def _step_streaming(self) -> Dict[str, Any]:
        """Streaming rollout→train step: N generator-task runners
        stream GAE'd rollout blocks straight into the learner's
        ``iter_batches`` (first epoch trains as blocks arrive; later
        epochs shuffle the collected batch). The consumer-idle
        fraction is reported as ``rollout_train_bubble``."""
        from ray_tpu.rllib.rollout_stream import (
            RolloutBlockStream, make_rollout_streams)
        cfg = self.config
        self._iterations += 1
        n_runners = max(1, cfg.num_env_runners)
        per_runner = max(1, cfg.train_batch_size
                         // (n_runners * cfg.num_envs_per_env_runner))
        block_steps = min(max(1, cfg.rollout_block_steps), per_runner)
        n_blocks = max(1, -(-per_runner // block_steps))
        weights_ref = ray_tpu.put(self.learner_group.get_weights())
        gens = make_rollout_streams(
            self._env_creator, self.module_spec, weights_ref,
            n_runners, n_blocks, block_steps,
            num_envs=cfg.num_envs_per_env_runner, gamma=cfg.gamma,
            lambda_=getattr(cfg, "lambda_", 0.95),
            # fresh trajectories every iteration, deterministic within
            # one (lineage replay must regenerate identical blocks)
            seed=cfg.seed + 100_000 * self._iterations)
        stream = RolloutBlockStream(gens, collect=True)
        try:
            metrics = self.learner_group.update_from_stream(
                stream, minibatch_size=cfg.minibatch_size,
                num_epochs=cfg.num_epochs)
        finally:
            stream.close()
        sstats = stream.stats()
        self._timesteps += int(sstats["rows"])
        self._cached_weights = None
        self._return_window.extend(stream.episode_returns())
        self._return_window = self._return_window[-100:]
        mean_return = (float(np.mean(self._return_window))
                       if self._return_window else float("nan"))
        return {
            "episode_return_mean": mean_return,
            "episode_reward_mean": mean_return,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "learner": metrics,
            "rollout_train_bubble": sstats["bubble"],
            "rollout_stream": sstats,
        }

    def train(self) -> Dict[str, Any]:
        result = super().train()
        result.setdefault("timesteps_total", self._timesteps)
        return result

    # -- checkpointing -------------------------------------------------
    def save_checkpoint(self, checkpoint_dir: str) -> str:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "wb") as f:
            pickle.dump({"weights": self.learner_group.get_weights(),
                         "timesteps": self._timesteps}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_weights(state["weights"])
        self._timesteps = state["timesteps"]
        self._sync_weights()

    def get_policy_weights(self):
        return self.learner_group.get_weights()

    def compute_single_action(self, obs: np.ndarray):
        if self._cached_weights is None:
            self._cached_weights = self.learner_group.get_weights()
        action = self._inference_module.forward_inference(
            self._cached_weights, np.asarray([obs]))
        if self.module_spec.is_continuous:
            return np.asarray(action[0])
        return int(action[0])

    def cleanup(self) -> None:
        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.learner_group.shutdown()

    stop = Trainable.stop
