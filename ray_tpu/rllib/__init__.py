"""ray_tpu.rllib: reinforcement learning (reference: ``rllib/``).

JAX-native learner stack: Algorithm (a Tune Trainable) drives parallel
EnvRunner actors and a jitted Learner/LearnerGroup. PPO is the flagship
algorithm; PG the minimal baseline.
"""

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.connectors import (
    ClipObs, Connector, ConnectorPipeline, FlattenObs, FrameStack,
    NormalizeObs)
from ray_tpu.rllib.cql import CQL, CQLConfig
from ray_tpu.rllib.ddpg import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.estimators import (
    DirectMethod, DoublyRobust, FQEModel, ImportanceSampling,
    WeightedImportanceSampling)
from ray_tpu.rllib.env_runner import EnvRunner, compute_gae
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv, MultiAgentEnvRunner, MultiAgentPPO,
    MultiAgentPPOConfig)
from ray_tpu.rllib.offline import (
    BC, BCConfig, MARWIL, MARWILConfig, JsonReader, JsonWriter)
from ray_tpu.rllib.pg import PG, PGConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec
from ray_tpu.rllib.rollout_stream import (
    RandomEnv, RolloutBlockStream, make_rollout_streams,
    rollout_stream)
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = [
    "APPO",
    "APPOConfig",
    "Algorithm",
    "AlgorithmConfig",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "ClipObs",
    "Connector",
    "ConnectorPipeline",
    "DDPG",
    "DDPGConfig",
    "DQN",
    "DQNConfig",
    "DirectMethod",
    "DoublyRobust",
    "EnvRunner",
    "FQEModel",
    "ImportanceSampling",
    "FlattenObs",
    "FrameStack",
    "IMPALA",
    "IMPALAConfig",
    "JsonReader",
    "JsonWriter",
    "Learner",
    "LearnerGroup",
    "MARWIL",
    "MARWILConfig",
    "MultiAgentEnv",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "NormalizeObs",
    "PG",
    "PGConfig",
    "PPO",
    "PPOConfig",
    "RLModule",
    "RLModuleSpec",
    "RandomEnv",
    "RolloutBlockStream",
    "SAC",
    "SACConfig",
    "TD3",
    "TD3Config",
    "WeightedImportanceSampling",
    "compute_gae",
    "make_rollout_streams",
    "rollout_stream",
]
