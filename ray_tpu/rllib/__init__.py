"""ray_tpu.rllib: reinforcement learning (reference: ``rllib/``).

JAX-native learner stack: Algorithm (a Tune Trainable) drives parallel
EnvRunner actors and a jitted Learner/LearnerGroup. PPO is the flagship
algorithm; PG the minimal baseline.
"""

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.env_runner import EnvRunner, compute_gae
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.pg import PG, PGConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "DQN",
    "DQNConfig",
    "EnvRunner",
    "Learner",
    "LearnerGroup",
    "PG",
    "PGConfig",
    "PPO",
    "PPOConfig",
    "RLModule",
    "RLModuleSpec",
    "compute_gae",
]
