"""Vanilla policy gradient (REINFORCE with baseline).

Reference: the (contrib) PG algorithm — simplest on-policy baseline,
sharing the PPO batch format/runner stack.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig


def pg_loss(fwd_out, batch, *, vf_loss_coeff: float = 0.5):
    logits = fwd_out["action_logits"]
    values = fwd_out["vf_preds"]
    logp_all = jax.nn.log_softmax(logits)
    logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
    adv = batch["advantages"]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    policy_loss = -jnp.mean(logp * adv)
    vf_loss = jnp.mean(jnp.square(values - batch["value_targets"]))
    total = policy_loss + vf_loss_coeff * vf_loss
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss}


class PGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PG)
        self.vf_loss_coeff: float = 0.5
        self.lambda_: float = 1.0
        self.num_epochs = 1


class PG(Algorithm):
    config_cls = PGConfig

    def loss_fn(self):
        return pg_loss

    def loss_config(self) -> Dict[str, Any]:
        return {"vf_loss_coeff": self.config.vf_loss_coeff}
