"""Offline RL: dataset IO + behavior cloning + MARWIL.

Reference: ``rllib/offline/`` (``json_reader.py``/``json_writer.py``
SampleBatch IO, ``dataset_reader.py``) and the algorithms
``rllib/algorithms/bc/bc.py`` and ``rllib/algorithms/marwil/marwil.py``
(advantage-weighted behavior cloning). TPU-native: both losses run on
the same jitted Learner stack as the online algorithms; the reader
hands out numpy batches, so training needs no environment at all.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, _resolve_env_creator
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.rl_module import RLModuleSpec


# ------------------------------------------------------------------ IO
class JsonWriter:
    """Writes rollout batches as JSON-lines episodes (reference:
    ``offline/json_writer.py`` — one SampleBatch per line)."""

    def __init__(self, path: str, max_file_size: int = 64 << 20):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.max_file_size = max_file_size
        self._index = 0
        self._f = None

    def _file(self):
        if self._f is None or self._f.tell() > self.max_file_size:
            if self._f:
                self._f.close()
            self._index += 1
            self._f = open(os.path.join(
                self.path, f"output-{self._index:05d}.json"), "w")
        return self._f

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        f = self._file()
        f.write(json.dumps(row) + "\n")
        f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


class JsonReader:
    """Reads JSON-lines batches; shuffles rows into sample batches."""

    def __init__(self, paths, seed: int = 0):
        if isinstance(paths, str):
            paths = [paths]
        files: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(_glob.glob(os.path.join(p, "*.json"))))
            else:
                files.extend(sorted(_glob.glob(p)) or [p])
        if not files:
            raise FileNotFoundError(f"no offline data under {paths!r}")
        batches = []
        for fp in files:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        batches.append({
                            k: np.asarray(v)
                            for k, v in json.loads(line).items()})
        self._data = {
            k: np.concatenate([b[k] for b in batches])
            for k in batches[0]}
        self._n = len(self._data["obs"])
        self._rng = np.random.default_rng(seed)

    @property
    def num_samples(self) -> int:
        return self._n

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._n, size=batch_size)
        return {k: v[idx] for k, v in self._data.items()}

    def iter_epochs(self, batch_size: int) -> Iterator[Dict[str, np.ndarray]]:
        perm = self._rng.permutation(self._n)
        for s in range(0, self._n, batch_size):
            idx = perm[s:s + batch_size]
            yield {k: v[idx] for k, v in self._data.items()}


def compute_monte_carlo_returns(rewards: np.ndarray, dones: np.ndarray,
                                gamma: float) -> np.ndarray:
    """Discounted returns per step (episode-bounded), for MARWIL's
    advantage estimate over offline data."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in reversed(range(len(rewards))):
        acc = rewards[t] + gamma * acc * (1.0 - dones[t])
        out[t] = acc
    return out


# -------------------------------------------------------------- losses
def bc_loss(fwd_out: Dict[str, jnp.ndarray],
            batch: Dict[str, jnp.ndarray], *,
            entropy_coeff: float = 0.0):
    logits = fwd_out["action_logits"]
    logp_all = jax.nn.log_softmax(logits)
    logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
    policy_loss = -jnp.mean(logp)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    total = policy_loss - entropy_coeff * entropy
    return total, {"policy_loss": policy_loss, "entropy": entropy}


def marwil_loss(fwd_out: Dict[str, jnp.ndarray],
                batch: Dict[str, jnp.ndarray], *,
                beta: float = 1.0,
                vf_loss_coeff: float = 1.0):
    """Advantage-weighted BC (reference: marwil torch learner): weight
    each log-prob by exp(beta * normalized advantage); advantages are
    monte-carlo return minus the learned value baseline."""
    logits = fwd_out["action_logits"]
    values = fwd_out["vf_preds"]
    logp_all = jax.nn.log_softmax(logits)
    logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
    adv = batch["returns"] - values
    vf_loss = 0.5 * jnp.mean(jnp.square(adv))
    adv_sg = jax.lax.stop_gradient(adv)
    norm = jnp.sqrt(jnp.mean(jnp.square(adv_sg)) + 1e-8)
    weights = jnp.exp(jnp.clip(beta * adv_sg / norm, -10.0, 10.0))
    policy_loss = -jnp.mean(jax.lax.stop_gradient(weights) * logp)
    total = policy_loss + vf_loss_coeff * vf_loss
    return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "mean_weight": jnp.mean(weights)}


# ---------------------------------------------------------- algorithms
class _OfflineAlgorithm(Algorithm):
    """Shared driver: no env runners; batches come from the reader.
    If ``config.env`` is set, each step also rolls out a few eval
    episodes to report ``episode_return_mean``."""

    def setup(self, _cfg: Dict) -> None:
        cfg = self.config = self._algo_config
        if not getattr(cfg, "offline_data", None):
            raise ValueError("offline algorithms need config.offline_data")
        self.reader = JsonReader(cfg.offline_data, seed=cfg.seed)
        self._prepare_reader_extras()

        obs_dim = int(np.prod(np.shape(
            self.reader._data["obs"][0])))
        num_actions = int(self.reader._data["actions"].max()) + 1
        if cfg.env is not None:
            env_creator = _resolve_env_creator(cfg.env, cfg.env_config)
            probe = env_creator()
            obs_dim = int(np.prod(probe.observation_space.shape))
            num_actions = int(probe.action_space.n)
            self._eval_env = env_creator()
        else:
            self._eval_env = None
        self.module_spec = RLModuleSpec(
            observation_dim=obs_dim, num_actions=num_actions,
            hiddens=tuple(cfg.model.get("fcnet_hiddens", (64, 64))))
        spec, loss_fn = self.module_spec, self.loss_fn()
        loss_config = self.loss_config()
        lr, clip, seed = cfg.lr, cfg.grad_clip, cfg.seed

        def make_learner() -> Learner:
            return Learner(spec, loss_fn, learning_rate=lr,
                           grad_clip=clip, seed=seed,
                           loss_config=loss_config)

        self.learner_group = LearnerGroup(
            make_learner, num_learners=cfg.num_learners, seed=cfg.seed)
        self._inference_module = spec.build()
        self._cached_weights = None
        self.env_runners = []
        self._timesteps = 0
        self._return_window: List[float] = []

    def _prepare_reader_extras(self) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.reader.sample(cfg.train_batch_size)
        metrics = self.learner_group.update_from_batch(
            batch, minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs)
        self._timesteps += cfg.train_batch_size
        out = {
            "num_env_steps_trained_lifetime": self._timesteps,
            "learner": metrics,
        }
        if self._eval_env is not None:
            out["episode_return_mean"] = self._evaluate(episodes=2)
            out["episode_reward_mean"] = out["episode_return_mean"]
        return out

    def _evaluate(self, episodes: int = 2) -> float:
        self._cached_weights = self.learner_group.get_weights()
        totals = []
        for _ in range(episodes):
            out = self._eval_env.reset()
            obs = out[0] if isinstance(out, tuple) else out
            total, done = 0.0, False
            for _ in range(1000):
                a = self._inference_module.forward_inference(
                    self._cached_weights, np.asarray([obs]))
                step = self._eval_env.step(int(a[0]))
                if len(step) == 5:
                    obs, r, term, trunc, _ = step
                    done = term or trunc
                else:
                    obs, r, done, _ = step
                total += float(r)
                if done:
                    break
            totals.append(total)
        self._return_window.extend(totals)
        self._return_window = self._return_window[-100:]
        return float(np.mean(self._return_window))

    def cleanup(self) -> None:
        if self._eval_env is not None:
            try:
                self._eval_env.close()
            except Exception:
                pass
        self.learner_group.shutdown()


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.offline_data: Optional[Any] = None
        self.entropy_coeff: float = 0.0
        self.lr = 1e-3
        self.num_epochs = 1
        self.minibatch_size = None
        self.env = None

    def offline_data_paths(self, paths) -> "BCConfig":
        self.offline_data = paths
        return self


class BC(_OfflineAlgorithm):
    config_cls = BCConfig

    def loss_fn(self):
        return bc_loss

    def loss_config(self) -> Dict[str, Any]:
        return {"entropy_coeff": self.config.entropy_coeff}


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.offline_data: Optional[Any] = None
        self.beta: float = 1.0
        self.vf_loss_coeff: float = 1.0
        self.lr = 1e-3
        self.num_epochs = 1
        self.minibatch_size = None
        self.env = None


class MARWIL(_OfflineAlgorithm):
    config_cls = MARWILConfig

    def loss_fn(self):
        return marwil_loss

    def loss_config(self) -> Dict[str, Any]:
        return {"beta": self.config.beta,
                "vf_loss_coeff": self.config.vf_loss_coeff}

    def _prepare_reader_extras(self) -> None:
        d = self.reader._data
        if "returns" not in d:
            d["returns"] = compute_monte_carlo_returns(
                d["rewards"].astype(np.float32),
                d["dones"].astype(np.float32), self.config.gamma)
