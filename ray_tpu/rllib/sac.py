"""SAC: soft actor-critic with twin Q networks and learned temperature
— continuous (tanh-squashed Gaussian) and discrete (categorical).

Reference: ``rllib/algorithms/sac/sac.py`` + the torch loss in
``sac/torch/sac_torch_learner.py`` (twin critics, polyak target sync,
entropy temperature tuned toward a target entropy) and the Box-space
Gaussian policy model in ``sac/sac_torch_model.py:15``. The continuous
path is the canonical SAC: reparameterized tanh-squashed samples,
Q(s, a) critics over concatenated state-action, target entropy
``-action_dim``. The discrete-action formulation follows Christodoulou
2019 (expectations over the action distribution instead of
reparameterized samples), matching what the reference's
``target_entropy="auto"`` machinery computes for ``Discrete`` spaces.
TPU-native shape: either way the whole update (both critic losses, the
policy loss, the temperature loss, three adams, and the polyak sync) is
one jitted XLA program.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNEnvRunner
from ray_tpu.rllib.models import init_mlp, mlp_forward, relu_mlp_forward
from ray_tpu.rllib.rl_module import RLModuleSpec


class SACEnvRunner(DQNEnvRunner):
    """Exploration = sampling from the categorical policy (reference:
    SAC explores with its stochastic policy; epsilon is ignored)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # install the stochastic forward ONCE: DQNEnvRunner.sample asks
        # forward_inference for actions, and SAC's actions are draws
        # from the softmax policy, not the argmax
        module = self._module
        rng = self._rng
        na = module.spec.num_actions

        def sample_policy(params, obs):
            import jax
            import jax.numpy as jnp
            from ray_tpu.rllib.models import actor_critic_forward
            logits, _ = actor_critic_forward(
                params, jnp.asarray(obs, jnp.float32))
            p = np.asarray(jax.nn.softmax(logits), np.float64)
            cum = np.cumsum(p, axis=-1)
            r = rng.random((p.shape[0], 1))
            # clamp: float cumsums can end below 1.0, and (r < cum)
            # all-False would silently argmax to action 0
            return np.minimum((r < cum).argmax(axis=-1)
                              + ((r >= cum[:, -1:]).ravel()
                                 * (na - 1)).astype(np.int64),
                              na - 1)

        module.forward_inference = sample_policy

    def sample(self, num_steps: int, epsilon: float = 0.0):
        return super().sample(num_steps, epsilon=0.0)


class SACLearner:
    """Twin soft Q + categorical policy + learned log-alpha, one jitted
    update with polyak target sync."""

    def __init__(self, module_spec: RLModuleSpec, *,
                 actor_lr: float, critic_lr: float, alpha_lr: float,
                 gamma: float, tau: float,
                 target_entropy: Optional[float],
                 grad_clip: Optional[float], seed: int):
        import jax
        import jax.numpy as jnp
        import optax
        self.module = module_spec.build()
        self._gamma = gamma
        self._tau = tau
        na = module_spec.num_actions
        # reference target_entropy="auto" for Discrete: 0.98 * log|A|
        self._target_entropy = target_entropy if target_entropy \
            is not None else 0.98 * math.log(na)

        def maybe_clip(tx):
            return optax.chain(optax.clip_by_global_norm(grad_clip),
                               tx) if grad_clip else tx

        self._pi_opt = maybe_clip(optax.adam(actor_lr))
        self._q_opt = maybe_clip(optax.adam(critic_lr))
        self._a_opt = optax.adam(alpha_lr)

        keys = jax.random.split(jax.random.PRNGKey(seed), 3)
        sizes = [module_spec.observation_dim,
                 *module_spec.hiddens, na]
        pi = self.module.init(keys[0])
        q1 = init_mlp(keys[1], sizes)
        q2 = init_mlp(keys[2], sizes)
        self._state = {
            "pi": pi, "q1": q1, "q2": q2,
            "q1_t": jax.tree.map(lambda x: x.copy(), q1),
            "q2_t": jax.tree.map(lambda x: x.copy(), q2),
            "log_alpha": jnp.zeros(()),
            "pi_opt": self._pi_opt.init(pi),
            "q_opt": self._q_opt.init({"q1": q1, "q2": q2}),
            "a_opt": self._a_opt.init(jnp.zeros(())),
        }
        self._jit_update = jax.jit(self._update, donate_argnums=(0,))

    def _policy_dist(self, pi_params, obs):
        import jax
        out = self.module.forward_train(pi_params, obs)
        logp = jax.nn.log_softmax(out["action_logits"])
        import jax.numpy as jnp
        return jnp.exp(logp), logp

    def _update(self, state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        obs, next_obs = batch["obs"], batch["next_obs"]
        acts = batch["actions"]
        alpha = jnp.exp(state["log_alpha"])

        # -- soft target: y = r + gamma * E_a'[minQt - alpha * logpi] --
        p_next, logp_next = self._policy_dist(state["pi"], next_obs)
        q1t = mlp_forward(state["q1_t"], next_obs)
        q2t = mlp_forward(state["q2_t"], next_obs)
        v_next = jnp.sum(
            p_next * (jnp.minimum(q1t, q2t) - alpha * logp_next), -1)
        y = batch["rewards"] + self._gamma \
            * (1.0 - batch["dones"]) * jax.lax.stop_gradient(v_next)

        def q_loss(qs):
            idx = jnp.arange(obs.shape[0])
            l1 = jnp.mean((mlp_forward(qs["q1"], obs)[idx, acts]
                           - y) ** 2)
            l2 = jnp.mean((mlp_forward(qs["q2"], obs)[idx, acts]
                           - y) ** 2)
            return l1 + l2, (l1, l2)

        (qf_loss, (l1, l2)), q_grads = jax.value_and_grad(
            q_loss, has_aux=True)({"q1": state["q1"],
                                   "q2": state["q2"]})
        q_updates, q_opt = self._q_opt.update(
            q_grads, state["q_opt"], {"q1": state["q1"],
                                      "q2": state["q2"]})
        qs = optax.apply_updates({"q1": state["q1"],
                                  "q2": state["q2"]}, q_updates)

        # -- policy: E_a[alpha * logpi - minQ] --------------------------
        def pi_loss(pi_params):
            p, logp = self._policy_dist(pi_params, obs)
            minq = jnp.minimum(mlp_forward(qs["q1"], obs),
                               mlp_forward(qs["q2"], obs))
            loss = jnp.mean(jnp.sum(
                p * (alpha * logp - jax.lax.stop_gradient(minq)), -1))
            entropy = -jnp.mean(jnp.sum(p * logp, -1))
            return loss, entropy

        (pl, entropy), pi_grads = jax.value_and_grad(
            pi_loss, has_aux=True)(state["pi"])
        pi_updates, pi_opt = self._pi_opt.update(
            pi_grads, state["pi_opt"], state["pi"])
        pi = optax.apply_updates(state["pi"], pi_updates)

        # -- temperature toward the target entropy ----------------------
        def a_loss(log_alpha):
            return jnp.exp(log_alpha) * jax.lax.stop_gradient(
                entropy - self._target_entropy)

        al, a_grad = jax.value_and_grad(a_loss)(state["log_alpha"])
        a_updates, a_opt = self._a_opt.update(
            a_grad, state["a_opt"], state["log_alpha"])
        log_alpha = optax.apply_updates(state["log_alpha"], a_updates)

        # -- polyak sync -------------------------------------------------
        tau = self._tau
        polyak = lambda t, o: jax.tree.map(  # noqa: E731
            lambda a, b: (1 - tau) * a + tau * b, t, o)

        metrics = {
            "qf_loss": qf_loss, "q1_loss": l1, "q2_loss": l2,
            "policy_loss": pl, "alpha_loss": al,
            "alpha": jnp.exp(log_alpha), "entropy": entropy,
            "total_loss": qf_loss + pl + al,
        }
        return {
            "pi": pi, "q1": qs["q1"], "q2": qs["q2"],
            "q1_t": polyak(state["q1_t"], qs["q1"]),
            "q2_t": polyak(state["q2_t"], qs["q2"]),
            "log_alpha": log_alpha,
            "pi_opt": pi_opt, "q_opt": q_opt, "a_opt": a_opt,
        }, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self._state, metrics = self._jit_update(self._state, jb)
        return {k: float(v) for k, v in metrics.items()}

    def update_many(self, batches):
        from ray_tpu.rllib.dqn import _scanned_update
        return _scanned_update(self, batches)

    def get_weights(self):
        # the runners need only the policy subtree
        return self._state["pi"]


class ContinuousSACEnvRunner(DQNEnvRunner):
    """Rollout actor for Box spaces: actions are reparameterized
    tanh-squashed Gaussian samples; the replay buffer stores the
    squashed action in (-1, 1) (what the critics see), the env gets it
    rescaled to the space bounds. The stepping loop is DQNEnvRunner's —
    only action selection and the env-action transform differ."""

    def __init__(self, env_creator, module_spec: RLModuleSpec,
                 num_envs: int = 1, seed: int = 0,
                 worker_index: int = 0):
        import jax
        super().__init__(env_creator, module_spec, num_envs, seed,
                         worker_index)
        self._key = jax.random.PRNGKey(seed * 10_003 + worker_index + 1)
        low = np.asarray(module_spec.action_low, np.float32)
        high = np.asarray(module_spec.action_high, np.float32)
        self._center = (low + high) / 2.0
        self._scale = (high - low) / 2.0

    def _make_act_buf(self, shape) -> np.ndarray:
        return np.zeros(shape + (self._module.spec.action_dim,),
                        np.float32)

    def _select_actions(self, epsilon: float) -> np.ndarray:
        import jax
        import jax.numpy as jnp
        from ray_tpu.rllib.models import (LOG_STD_MAX, LOG_STD_MIN,
                                          relu_mlp_forward)
        self._key, sub = jax.random.split(self._key)
        out = relu_mlp_forward(self._params,
                               jnp.asarray(self._obs, jnp.float32))
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        u = mean + jnp.exp(log_std) * jax.random.normal(
            sub, mean.shape, mean.dtype)
        return np.asarray(jnp.tanh(u), np.float32)

    def _env_action(self, action):
        return self._center + self._scale * action


class ContinuousSACLearner:
    """Canonical SAC (Haarnoja 2018, as in the reference's torch
    learner): twin Q(s, a), tanh-squashed reparameterized policy,
    learned temperature toward target entropy -|A|. One jitted update."""

    def __init__(self, module_spec: RLModuleSpec, *,
                 actor_lr: float, critic_lr: float, alpha_lr: float,
                 gamma: float, tau: float,
                 target_entropy: Optional[float],
                 grad_clip: Optional[float], seed: int):
        import jax
        import jax.numpy as jnp
        import optax
        self.spec = module_spec
        self._gamma = gamma
        self._tau = tau
        adim = module_spec.action_dim
        self._target_entropy = (target_entropy if target_entropy
                                is not None else -float(adim))

        def maybe_clip(tx):
            return optax.chain(optax.clip_by_global_norm(grad_clip),
                               tx) if grad_clip else tx

        self._pi_opt = maybe_clip(optax.adam(actor_lr))
        self._q_opt = maybe_clip(optax.adam(critic_lr))
        self._a_opt = optax.adam(alpha_lr)

        keys = jax.random.split(jax.random.PRNGKey(seed), 3)
        obs_dim = module_spec.observation_dim
        h = list(module_spec.hiddens)
        pi = init_mlp(keys[0], [obs_dim, *h, 2 * adim], scale=0.01)
        q_sizes = [obs_dim + adim, *h, 1]
        q1 = init_mlp(keys[1], q_sizes)
        q2 = init_mlp(keys[2], q_sizes)
        self._state = {
            "pi": pi, "q1": q1, "q2": q2,
            "q1_t": jax.tree.map(lambda x: x.copy(), q1),
            "q2_t": jax.tree.map(lambda x: x.copy(), q2),
            "log_alpha": jnp.zeros(()),
            "pi_opt": self._pi_opt.init(pi),
            "q_opt": self._q_opt.init({"q1": q1, "q2": q2}),
            "a_opt": self._a_opt.init(jnp.zeros(())),
            "key": keys[0],
        }
        self._jit_update = jax.jit(self._update, donate_argnums=(0,))

    @staticmethod
    def _pi_sample(pi_params, obs, key):
        """Reparameterized squashed sample + its log-prob."""
        import jax.numpy as jnp
        from ray_tpu.rllib.models import (LOG_STD_MAX, LOG_STD_MIN,
                                          squashed_gaussian_sample)
        out = relu_mlp_forward(pi_params, obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        return squashed_gaussian_sample(key, mean, log_std)

    @staticmethod
    def _q(q_params, obs, act):
        import jax.numpy as jnp
        return relu_mlp_forward(
            q_params, jnp.concatenate([obs, act], -1))[..., 0]

    def _update(self, state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        obs, next_obs = batch["obs"], batch["next_obs"]
        acts = batch["actions"]
        alpha = jnp.exp(state["log_alpha"])
        key, k_next, k_pi = jax.random.split(state["key"], 3)

        # -- critic target: y = r + g (minQt(s', a') - a logpi(a'|s'))
        a_next, logp_next = self._pi_sample(state["pi"], next_obs,
                                            k_next)
        q_next = jnp.minimum(self._q(state["q1_t"], next_obs, a_next),
                             self._q(state["q2_t"], next_obs, a_next))
        y = batch["rewards"] + self._gamma * (1.0 - batch["dones"]) \
            * jax.lax.stop_gradient(q_next - alpha * logp_next)

        def q_loss(qs):
            l1 = jnp.mean((self._q(qs["q1"], obs, acts) - y) ** 2)
            l2 = jnp.mean((self._q(qs["q2"], obs, acts) - y) ** 2)
            return l1 + l2, (l1, l2)

        (qf_loss, (l1, l2)), q_grads = jax.value_and_grad(
            q_loss, has_aux=True)({"q1": state["q1"],
                                   "q2": state["q2"]})
        q_updates, q_opt = self._q_opt.update(
            q_grads, state["q_opt"], {"q1": state["q1"],
                                      "q2": state["q2"]})
        qs = optax.apply_updates({"q1": state["q1"],
                                  "q2": state["q2"]}, q_updates)

        # -- policy: E[alpha * logpi(a|s) - minQ(s, a)], a reparam'd --
        def pi_loss(pi_params):
            a, logp = self._pi_sample(pi_params, obs, k_pi)
            minq = jnp.minimum(self._q(qs["q1"], obs, a),
                               self._q(qs["q2"], obs, a))
            return jnp.mean(alpha * logp - minq), -jnp.mean(logp)

        (pl, entropy), pi_grads = jax.value_and_grad(
            pi_loss, has_aux=True)(state["pi"])
        pi_updates, pi_opt = self._pi_opt.update(
            pi_grads, state["pi_opt"], state["pi"])
        pi = optax.apply_updates(state["pi"], pi_updates)

        # -- temperature toward target entropy -|A| -------------------
        def a_loss(log_alpha):
            return -jnp.exp(log_alpha) * jax.lax.stop_gradient(
                self._target_entropy - entropy)

        al, a_grad = jax.value_and_grad(a_loss)(state["log_alpha"])
        a_updates, a_opt = self._a_opt.update(
            a_grad, state["a_opt"], state["log_alpha"])
        log_alpha = optax.apply_updates(state["log_alpha"], a_updates)

        tau = self._tau
        polyak = lambda t, o: jax.tree.map(  # noqa: E731
            lambda a, b: (1 - tau) * a + tau * b, t, o)
        metrics = {
            "qf_loss": qf_loss, "q1_loss": l1, "q2_loss": l2,
            "policy_loss": pl, "alpha_loss": al,
            "alpha": jnp.exp(log_alpha), "entropy": entropy,
            "total_loss": qf_loss + pl + al,
        }
        return {
            "pi": pi, "q1": qs["q1"], "q2": qs["q2"],
            "q1_t": polyak(state["q1_t"], qs["q1"]),
            "q2_t": polyak(state["q2_t"], qs["q2"]),
            "log_alpha": log_alpha,
            "pi_opt": pi_opt, "q_opt": q_opt, "a_opt": a_opt,
            "key": key,
        }, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self._state, metrics = self._jit_update(self._state, jb)
        return {k: float(v) for k, v in metrics.items()}

    def update_many(self, batches):
        from ray_tpu.rllib.dqn import _scanned_update
        return _scanned_update(self, batches)

    def get_weights(self):
        return self._state["pi"]


class SACConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.lr = 3e-4                  # actor lr
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.tau = 0.01
        self.target_entropy: Optional[float] = None   # auto
        self.train_batch_size = 64
        self.num_steps_sampled_before_learning_starts = 500
        self.updates_per_step = 4


class SAC(DQN):
    config_cls = SACConfig
    supports_continuous = True

    def _make_learner(self):
        cfg = self.config
        cls = ContinuousSACLearner if self.module_spec.is_continuous \
            else SACLearner
        return cls(
            self.module_spec, actor_lr=cfg.lr, critic_lr=cfg.critic_lr,
            alpha_lr=cfg.alpha_lr, gamma=cfg.gamma, tau=cfg.tau,
            target_entropy=cfg.target_entropy, grad_clip=cfg.grad_clip,
            seed=cfg.seed)

    def _runner_cls(self):
        if self.module_spec.is_continuous:
            return ContinuousSACEnvRunner
        return SACEnvRunner

    def compute_single_action(self, obs: np.ndarray):
        if not self.module_spec.is_continuous:
            return super().compute_single_action(obs)
        import jax.numpy as jnp
        from ray_tpu.rllib.models import relu_mlp_forward as _fwd
        out = _fwd(self.learner.get_weights(),
                   jnp.asarray(obs[None], jnp.float32))
        mean = np.asarray(jnp.split(out, 2, axis=-1)[0][0])
        low = np.asarray(self.module_spec.action_low, np.float32)
        high = np.asarray(self.module_spec.action_high, np.float32)
        center, scale = (low + high) / 2.0, (high - low) / 2.0
        return center + scale * np.tanh(mean)
