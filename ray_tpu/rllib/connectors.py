"""Connector pipelines: composable obs/action transforms.

Reference: ``rllib/connectors/`` (ConnectorV2 pipelines that sit
between env and module on the rollout side, and between dataset and
learner on the training side). Each connector is a pure callable over
numpy batches so runners stay picklable and the module keeps seeing
plain arrays.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One stage; subclasses override __call__(batch_of_obs)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def state(self) -> Dict[str, Any]:
        """Serializable state, synced runner<->learner like weights."""
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c(obs)
        return obs

    def state(self) -> Dict[str, Any]:
        return {i: c.state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


class FlattenObs(Connector):
    """Flatten any trailing obs dims to one feature axis (reference:
    connectors' flatten_observations)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        return obs.reshape(obs.shape[0], -1)


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return np.clip(obs, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/variance normalization (reference:
    ``connectors/common/mean_std_filter.py`` — Welford accumulation,
    state synced across runners via the weight broadcast)."""

    def __init__(self, eps: float = 1e-8, clip: Optional[float] = 10.0):
        self.eps = eps
        self.clip = clip
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.ones(obs.shape[1:], np.float64)
        for row in obs:  # batches are small on the rollout path
            self._count += 1.0
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        var = self._m2 / max(self._count, 2.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    def state(self) -> Dict[str, Any]:
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class FrameStack(Connector):
    """Stack the last k observations along the feature axis (reference:
    connectors' framestacking for velocity-free envs)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: Optional[List[np.ndarray]] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        # copy: callers (EnvRunner) mutate their obs buffer in place —
        # storing references would alias every frame to the current obs
        obs = np.array(obs, np.float32, copy=True)
        if self._frames is None or self._frames[0].shape != obs.shape:
            self._frames = [obs] * self.k
        else:
            self._frames = self._frames[1:] + [obs]
        return np.concatenate(self._frames, axis=-1)
