"""Policy/value networks as pure JAX functions.

Reference: ``rllib/models/`` (catalog + torch/tf networks) — here a
single functional MLP family: params are dict pytrees, forwards are
pure, so the whole learner update jits and the same params ship to CPU
env-runners as numpy for rollout inference.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_mlp(key, sizes: Sequence[int], scale: float = 1.0) -> List[Dict]:
    layers = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        last = i == len(sizes) - 2
        w_scale = (scale if last else 1.0) * np.sqrt(2.0 / fan_in)
        layers.append({
            "w": w_scale * jax.random.normal(
                sub, (fan_in, fan_out), jnp.float32),
            "b": jnp.zeros((fan_out,), jnp.float32),
        })
    return layers


def mlp_forward(layers: List[Dict], x: jnp.ndarray,
                activation=jax.nn.tanh) -> jnp.ndarray:
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1:
            x = activation(x)
    return x


def init_actor_critic(key, obs_dim: int, num_actions: int,
                      hiddens: Sequence[int] = (64, 64)) -> Dict:
    k1, k2 = jax.random.split(key)
    return {
        "pi": init_mlp(k1, [obs_dim, *hiddens, num_actions], scale=0.01),
        "vf": init_mlp(k2, [obs_dim, *hiddens, 1], scale=1.0),
    }


def actor_critic_forward(params: Dict, obs: jnp.ndarray
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (logits [B, A], value [B])."""
    logits = mlp_forward(params["pi"], obs)
    value = mlp_forward(params["vf"], obs)[..., 0]
    return logits, value
