"""Policy/value networks as pure JAX functions.

Reference: ``rllib/models/`` (catalog + torch/tf networks) — here a
single functional MLP family: params are dict pytrees, forwards are
pure, so the whole learner update jits and the same params ship to CPU
env-runners as numpy for rollout inference.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_mlp(key, sizes: Sequence[int], scale: float = 1.0) -> List[Dict]:
    layers = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        last = i == len(sizes) - 2
        w_scale = (scale if last else 1.0) * np.sqrt(2.0 / fan_in)
        layers.append({
            "w": w_scale * jax.random.normal(
                sub, (fan_in, fan_out), jnp.float32),
            "b": jnp.zeros((fan_out,), jnp.float32),
        })
    return layers


def mlp_forward(layers: List[Dict], x: jnp.ndarray,
                activation=jax.nn.tanh) -> jnp.ndarray:
    for i, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if i < len(layers) - 1:
            x = activation(x)
    return x


def relu_mlp_forward(layers: List[Dict], x: jnp.ndarray) -> jnp.ndarray:
    """ReLU MLP: the continuous-control nets (SAC/DDPG/TD3/CQL critics
    and actors) use ReLU like the reference's torch models — tanh
    hidden layers saturate regressing the large-magnitude Q targets of
    reward-dense control tasks (Pendulum returns reach -1600)."""
    return mlp_forward(layers, x, activation=jax.nn.relu)


def init_actor_critic(key, obs_dim: int, num_actions: int,
                      hiddens: Sequence[int] = (64, 64)) -> Dict:
    k1, k2 = jax.random.split(key)
    return {
        "pi": init_mlp(k1, [obs_dim, *hiddens, num_actions], scale=0.01),
        "vf": init_mlp(k2, [obs_dim, *hiddens, 1], scale=1.0),
    }


def actor_critic_forward(params: Dict, obs: jnp.ndarray
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (logits [B, A], value [B])."""
    logits = mlp_forward(params["pi"], obs)
    value = mlp_forward(params["vf"], obs)[..., 0]
    return logits, value


# ---------------------------------------------------------------- Box spaces
# Diagonal-Gaussian policies for continuous control (reference:
# ``rllib/models/torch/torch_distributions.py`` TorchDiagGaussian /
# TorchSquashedGaussian, and ``sac/sac_torch_model.py:15`` which builds
# Box-space Gaussian heads). One pi MLP emits [mean, log_std] so PPO and
# SAC share the head; the squashed variants add the tanh log-det
# correction SAC's entropy term needs.

LOG_STD_MIN = -20.0
LOG_STD_MAX = 2.0
_LOG_2PI = float(np.log(2.0 * np.pi))


def init_gaussian_actor_critic(key, obs_dim: int, action_dim: int,
                               hiddens: Sequence[int] = (64, 64)) -> Dict:
    k1, k2 = jax.random.split(key)
    return {
        "pi": init_mlp(k1, [obs_dim, *hiddens, 2 * action_dim],
                       scale=0.01),
        "vf": init_mlp(k2, [obs_dim, *hiddens, 1], scale=1.0),
    }


def gaussian_actor_critic_forward(params: Dict, obs: jnp.ndarray
                                  ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                             jnp.ndarray]:
    """Returns (mean [B, A], log_std [B, A], value [B])."""
    out = mlp_forward(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    value = mlp_forward(params["vf"], obs)[..., 0]
    return mean, log_std, value


def diag_gaussian_logp(mean: jnp.ndarray, log_std: jnp.ndarray,
                       x: jnp.ndarray) -> jnp.ndarray:
    """Log-density of x under N(mean, diag(exp(log_std)^2)); sums the
    action axis -> [B]."""
    z = (x - mean) * jnp.exp(-log_std)
    return jnp.sum(-0.5 * z ** 2 - log_std - 0.5 * _LOG_2PI, axis=-1)


def diag_gaussian_entropy(log_std: jnp.ndarray) -> jnp.ndarray:
    """Entropy of the diagonal Gaussian, summed over actions -> [B]."""
    return jnp.sum(log_std + 0.5 * (_LOG_2PI + 1.0), axis=-1)


def tanh_logp_correction(pre_tanh: jnp.ndarray) -> jnp.ndarray:
    """log|det d tanh(u)/du| summed over the action axis -> [B].
    Numerically-stable form: log(1 - tanh(u)^2)
    = 2 * (log 2 - u - softplus(-2u))."""
    return jnp.sum(
        2.0 * (jnp.log(2.0) - pre_tanh
               - jax.nn.softplus(-2.0 * pre_tanh)), axis=-1)


def squashed_gaussian_sample(key, mean: jnp.ndarray, log_std: jnp.ndarray
                             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reparameterized tanh-squashed sample; returns (action in (-1, 1),
    log-prob [B] with the tanh correction applied)."""
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
    logp = diag_gaussian_logp(mean, log_std, u) - tanh_logp_correction(u)
    return jnp.tanh(u), logp
