"""AlgorithmConfig: fluent RL configuration.

Reference: ``rllib/algorithms/algorithm_config.py`` — chained
``.environment().env_runners().training().learners()`` calls producing
the Algorithm. ``build()`` returns the ready Algorithm instance.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Any = None
        self.env_config: Dict[str, Any] = {}
        # env runners
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        #: stream rollout blocks from generator-task runners straight
        #: into the learner (rollout_stream.py) instead of the
        #: epoch-barriered sample-then-train step. Lineage-replayable:
        #: a runner SIGKILLed mid-epoch replays its stream prefix.
        self.streaming_rollouts: bool = False
        #: env steps per streamed rollout block (per runner)
        self.rollout_block_steps: int = 64
        # training
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 4000
        self.minibatch_size: Optional[int] = 128
        self.num_epochs: int = 8
        self.grad_clip: Optional[float] = 0.5
        self.model: Dict[str, Any] = {"fcnet_hiddens": (64, 64)}
        # learners
        self.num_learners: int = 0
        # debugging
        self.seed: int = 0

    # -- fluent sections (each returns self) ---------------------------
    def environment(self, env=None, *, env_config: Optional[dict] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    streaming_rollouts: Optional[bool] = None,
                    rollout_block_steps: Optional[int] = None,
                    **_ignored) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if streaming_rollouts is not None:
            self.streaming_rollouts = streaming_rollouts
        if rollout_block_steps is not None:
            self.rollout_block_steps = rollout_block_steps
        return self

    # Reference alias
    rollouts = env_runners

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 train_batch_size: Optional[int] = None,
                 minibatch_size: Optional[int] = None,
                 num_epochs: Optional[int] = None,
                 grad_clip: Optional[float] = None,
                 model: Optional[dict] = None,
                 **kwargs) -> "AlgorithmConfig":
        for name, v in dict(lr=lr, gamma=gamma,
                            train_batch_size=train_batch_size,
                            minibatch_size=minibatch_size,
                            num_epochs=num_epochs,
                            grad_clip=grad_clip).items():
            if v is not None:
                setattr(self, name, v)
        if model is not None:
            self.model.update(model)
        for k, v in kwargs.items():  # algo-specific knobs
            setattr(self, k, v)
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 **_ignored) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def debugging(self, *, seed: Optional[int] = None,
                  **_ignored) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def resources(self, **_ignored) -> "AlgorithmConfig":
        return self

    def framework(self, *_a, **_k) -> "AlgorithmConfig":
        return self  # always JAX here

    # -- build ----------------------------------------------------------
    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in vars(self).items()
                if k != "algo_class"}

    def build(self):
        if self.algo_class is None:
            raise ValueError("No algo_class bound to this config")
        return self.algo_class(config=self)
