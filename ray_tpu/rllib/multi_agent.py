"""Multi-agent RL: shared environments, per-policy learners.

Reference: ``rllib/env/multi_agent_env.py`` (dict-keyed obs/action
protocol with ``__all__`` termination), ``rllib/policy/policy_map.py``
+ ``policy_mapping_fn`` (agent → policy routing), and the new stack's
``MultiRLModule`` (``core/rl_module/marl_module.py``). TPU-native: one
jitted Learner per policy; each policy's update is its own donated-state
XLA program, and rollouts route per-agent transitions to per-policy GAE
segments host-side (tiny, latency-bound work).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, _resolve_env_creator
from ray_tpu.rllib.env_runner import compute_gae
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.ppo import PPOConfig, ppo_loss
from ray_tpu.rllib.rl_module import RLModuleSpec


class MultiAgentEnv:
    """Dict-keyed environment protocol (reference:
    ``multi_agent_env.py``): ``reset() -> (obs_dict, info)``;
    ``step(action_dict) -> (obs, rewards, terminateds, truncateds,
    infos)`` where each is keyed by agent id and ``terminateds`` carries
    the special ``"__all__"`` flag."""

    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def close(self) -> None:
        pass


class MultiAgentEnvRunner:
    """Rollout actor for MultiAgentEnv: routes each agent's transitions
    to its policy's batch and computes per-agent GAE at segment end."""

    def __init__(self, env_creator, specs: Dict[str, RLModuleSpec],
                 policy_mapping_fn, gamma: float = 0.99,
                 lambda_: float = 0.95, seed: int = 0,
                 worker_index: int = 0):
        import jax
        self._env = env_creator()
        self._modules = {pid: spec.build() for pid, spec in specs.items()}
        self._params: Dict[str, Any] = {}
        self._map = policy_mapping_fn
        self._gamma, self._lambda = gamma, lambda_
        self._key = jax.random.PRNGKey(seed * 10_003 + worker_index)
        out = self._env.reset(seed=seed * 7919 + worker_index)
        self._obs = out[0] if isinstance(out, tuple) else out
        self._ep_return = 0.0
        self._completed: List[float] = []

    def set_weights(self, params_by_policy: Dict[str, Any]) -> None:
        self._params = params_by_policy

    def sample(self, num_steps: int) -> Dict[str, Dict[str, np.ndarray]]:
        """Returns {policy_id: flat_batch} with GAE computed per agent."""
        import jax
        traj = defaultdict(lambda: defaultdict(list))  # agent -> field
        for _ in range(num_steps):
            actions, logps, values = {}, {}, {}
            for aid, ob in self._obs.items():
                pid = self._map(aid)
                self._key, sub = jax.random.split(self._key)
                a, lp, v = self._modules[pid].forward_exploration(
                    self._params[pid], np.asarray([ob], np.float32), sub)
                actions[aid] = int(a[0])
                logps[aid] = float(lp[0])
                values[aid] = float(v[0])
            obs2, rews, terms, truncs, _ = self._env.step(actions)
            for aid in actions:
                t = traj[aid]
                t["obs"].append(np.asarray(self._obs[aid], np.float32))
                t["actions"].append(actions[aid])
                t["logp"].append(logps[aid])
                t["values"].append(values[aid])
                t["rewards"].append(float(rews.get(aid, 0.0)))
                done = bool(terms.get(aid) or truncs.get(aid)
                            or terms.get("__all__"))
                t["dones"].append(float(done))
                self._ep_return += float(rews.get(aid, 0.0))
            if terms.get("__all__") or truncs.get("__all__"):
                self._completed.append(self._ep_return)
                self._ep_return = 0.0
                out = self._env.reset()
                self._obs = out[0] if isinstance(out, tuple) else out
            else:
                self._obs = obs2

        by_policy: Dict[str, Dict[str, List]] = defaultdict(
            lambda: defaultdict(list))
        for aid, t in traj.items():
            pid = self._map(aid)
            rewards = np.asarray(t["rewards"], np.float32)
            values = np.asarray(t["values"], np.float32)
            dones = np.asarray(t["dones"], np.float32)
            # bootstrap with the policy's value of the agent's last obs
            if aid in self._obs and self._params.get(pid) is not None:
                import jax
                self._key, sub = jax.random.split(self._key)
                _, _, bv = self._modules[pid].forward_exploration(
                    self._params[pid],
                    np.asarray([self._obs[aid]], np.float32), sub)
                last_value = float(bv[0]) * (1.0 - dones[-1])
            else:
                last_value = 0.0
            adv, ret = compute_gae(rewards, values, dones, last_value,
                                   self._gamma, self._lambda)
            p = by_policy[pid]
            p["obs"].extend(t["obs"])
            p["actions"].extend(t["actions"])
            p["logp"].extend(t["logp"])
            p["advantages"].extend(adv.tolist())
            p["value_targets"].extend(ret.tolist())
        return {
            pid: {"obs": np.stack(b["obs"]),
                  "actions": np.asarray(b["actions"], np.int64),
                  "logp": np.asarray(b["logp"], np.float32),
                  "advantages": np.asarray(b["advantages"], np.float32),
                  "value_targets": np.asarray(b["value_targets"],
                                              np.float32)}
            for pid, b in by_policy.items()}

    def episode_returns(self, clear: bool = True) -> list:
        out = list(self._completed)
        if clear:
            self._completed = []
        return out


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MultiAgentPPO)
        #: policy_id -> dict(observation_dim=..., num_actions=...) or {}
        #: ({} = probe the env's per-agent spaces)
        self.policies: Dict[str, dict] = {}
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: aid

    def multi_agent(self, *, policies=None, policy_mapping_fn=None,
                    **_ignored) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = ({p: {} for p in policies}
                             if not isinstance(policies, dict)
                             else policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self


class MultiAgentPPO(Algorithm):
    """PPO over per-policy jitted learners (reference: multi-agent PPO
    via PolicyMap; here each policy owns an independent Learner)."""

    config_cls = MultiAgentPPOConfig

    def setup(self, _cfg: Dict) -> None:
        cfg = self.config = self._algo_config
        if not cfg.policies:
            raise ValueError("MultiAgentPPO needs config.policies")
        env_creator = _resolve_env_creator(cfg.env, cfg.env_config)
        probe = env_creator()
        out = probe.reset()
        obs0 = out[0] if isinstance(out, tuple) else out
        mapping = cfg.policy_mapping_fn

        specs: Dict[str, RLModuleSpec] = {}
        for pid, p_spec in cfg.policies.items():
            if p_spec.get("observation_dim"):
                obs_dim = p_spec["observation_dim"]
                n_act = p_spec["num_actions"]
            else:
                # probe: first agent mapped to this policy
                aid = next(a for a in obs0 if mapping(a) == pid)
                obs_dim = int(np.prod(np.shape(obs0[aid])))
                n_act = int(probe.action_spaces[aid].n) \
                    if hasattr(probe, "action_spaces") \
                    else int(p_spec.get("num_actions", 2))
            specs[pid] = RLModuleSpec(
                observation_dim=obs_dim, num_actions=n_act,
                hiddens=tuple(cfg.model.get("fcnet_hiddens", (64, 64))))
        probe.close()
        self._specs = specs

        loss_config = self.loss_config()
        self.learners = {
            pid: Learner(spec, ppo_loss, learning_rate=cfg.lr,
                         grad_clip=cfg.grad_clip, seed=cfg.seed + i,
                         loss_config=loss_config)
            for i, (pid, spec) in enumerate(specs.items())}

        n_runners = max(1, cfg.num_env_runners)
        runner_cls = ray_tpu.remote(num_cpus=1)(MultiAgentEnvRunner)
        self.env_runners = [
            runner_cls.remote(env_creator, specs, mapping, cfg.gamma,
                              cfg.lambda_, cfg.seed, i)
            for i in range(n_runners)]
        self._sync_weights()
        self._timesteps = 0
        self._return_window: List[float] = []

    def loss_config(self) -> Dict[str, Any]:
        c = self.config
        return {"clip_param": c.clip_param,
                "vf_loss_coeff": c.vf_loss_coeff,
                "entropy_coeff": c.entropy_coeff,
                "vf_clip_param": c.vf_clip_param}

    def _sync_weights(self) -> None:
        weights = {pid: l.get_weights()
                   for pid, l in self.learners.items()}
        ref = ray_tpu.put(weights)
        ray_tpu.get([r.set_weights.remote(ref)
                     for r in self.env_runners])

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        per_runner = max(1, cfg.train_batch_size // len(self.env_runners))
        samples = ray_tpu.get(
            [r.sample.remote(per_runner) for r in self.env_runners])
        metrics: Dict[str, Any] = {}
        for pid, learner in self.learners.items():
            parts = [s[pid] for s in samples if pid in s]
            if not parts:
                continue
            batch = {k: np.concatenate([p[k] for p in parts])
                     for k in parts[0]}
            self._timesteps += len(batch["obs"])
            mb = cfg.minibatch_size or len(batch["obs"])
            for _ in range(cfg.num_epochs):
                perm = np.random.permutation(len(batch["obs"]))
                for s in range(0, len(perm), mb):
                    idx = perm[s:s + mb]
                    metrics[pid] = learner.update_from_batch(
                        {k: v[idx] for k, v in batch.items()})
        self._sync_weights()

        returns: List[float] = []
        for r in ray_tpu.get(
                [r.episode_returns.remote() for r in self.env_runners]):
            returns.extend(r)
        self._return_window.extend(returns)
        self._return_window = self._return_window[-100:]
        mean_return = (float(np.mean(self._return_window))
                       if self._return_window else float("nan"))
        return {"episode_return_mean": mean_return,
                "episode_reward_mean": mean_return,
                "num_env_steps_sampled_lifetime": self._timesteps,
                "learner": metrics}

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "wb") as f:
            pickle.dump({
                "weights": {pid: l.get_weights()
                            for pid, l in self.learners.items()},
                "timesteps": self._timesteps}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        for pid, w in state["weights"].items():
            self.learners[pid].set_weights(w)
        self._timesteps = state["timesteps"]
        self._sync_weights()

    def cleanup(self) -> None:
        for r in self.env_runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
