"""CQL: conservative Q-learning for offline continuous control.

Reference: ``rllib/algorithms/cql/cql.py`` (+
``cql/torch/cql_torch_learner.py``): SAC's twin-critic machinery plus
the conservative regularizer — logsumexp of Q over sampled actions
(random + policy) minus Q on the dataset actions — trained purely from
an offline dataset, with optional environment evaluation rollouts.
TPU-native: the whole update (SAC losses + the CQL penalty with its
action sampling) is one jitted XLA program over reader batches.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import _resolve_env_creator, spec_for_spaces
from ray_tpu.rllib.offline import JsonReader
from ray_tpu.rllib.sac import ContinuousSACLearner, SACConfig
from ray_tpu.tune.trainable import Trainable


class CQLLearner(ContinuousSACLearner):
    """SAC learner + the CQL(H) penalty on both critics."""

    def __init__(self, module_spec, *, cql_alpha: float = 1.0,
                 cql_n_actions: int = 4, **kw):
        self._cql_alpha = cql_alpha
        self._cql_n = cql_n_actions
        super().__init__(module_spec, **kw)

    def _update(self, state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        obs, next_obs = batch["obs"], batch["next_obs"]
        acts = batch["actions"]
        alpha = jnp.exp(state["log_alpha"])
        key, k_next, k_pi, k_rand, k_cur = jax.random.split(
            state["key"], 5)
        B = obs.shape[0]
        A = self.spec.action_dim
        N = self._cql_n

        a_next, logp_next = self._pi_sample(state["pi"], next_obs,
                                            k_next)
        q_next = jnp.minimum(self._q(state["q1_t"], next_obs, a_next),
                             self._q(state["q2_t"], next_obs, a_next))
        y = batch["rewards"] + self._gamma * (1.0 - batch["dones"]) \
            * jax.lax.stop_gradient(q_next - alpha * logp_next)

        # CQL action samples: N uniform in (-1,1) and N from the current
        # policy, evaluated per-state (reference: cql_torch_learner's
        # repeated actions for the logsumexp term)
        rand_a = jax.random.uniform(k_rand, (N, B, A), minval=-1.0,
                                    maxval=1.0)
        pol_a, pol_logp = jax.vmap(
            lambda k: self._pi_sample(state["pi"], obs, k))(
            jax.random.split(k_cur, N))
        pol_a = jax.lax.stop_gradient(pol_a)
        pol_logp = jax.lax.stop_gradient(pol_logp)

        def q_loss(qs):
            td1 = jnp.mean((self._q(qs["q1"], obs, acts) - y) ** 2)
            td2 = jnp.mean((self._q(qs["q2"], obs, acts) - y) ** 2)

            def penalty(qp):
                q_rand = jax.vmap(
                    lambda a: self._q(qp, obs, a))(rand_a)   # [N, B]
                q_pol = jax.vmap(
                    lambda a: self._q(qp, obs, a))(pol_a)    # [N, B]
                # importance-correct the samples (CQL(H)): uniform
                # density is 0.5^A; policy samples use their log-prob
                stacked = jnp.concatenate([
                    q_rand - A * jnp.log(0.5),
                    q_pol - pol_logp], axis=0)               # [2N, B]
                lse = jax.scipy.special.logsumexp(
                    stacked, axis=0) - jnp.log(2 * N)
                return jnp.mean(lse - self._q(qp, obs, acts))

            cql1 = penalty(qs["q1"])
            cql2 = penalty(qs["q2"])
            total = td1 + td2 + self._cql_alpha * (cql1 + cql2)
            return total, (td1 + td2, cql1 + cql2)

        (qf_total, (td_loss, cql_loss)), q_grads = jax.value_and_grad(
            q_loss, has_aux=True)({"q1": state["q1"],
                                   "q2": state["q2"]})
        q_updates, q_opt = self._q_opt.update(
            q_grads, state["q_opt"], {"q1": state["q1"],
                                      "q2": state["q2"]})
        qs = optax.apply_updates({"q1": state["q1"],
                                  "q2": state["q2"]}, q_updates)

        def pi_loss(pi_params):
            a, logp = self._pi_sample(pi_params, obs, k_pi)
            minq = jnp.minimum(self._q(qs["q1"], obs, a),
                               self._q(qs["q2"], obs, a))
            return jnp.mean(alpha * logp - minq), -jnp.mean(logp)

        (pl, entropy), pi_grads = jax.value_and_grad(
            pi_loss, has_aux=True)(state["pi"])
        pi_updates, pi_opt = self._pi_opt.update(
            pi_grads, state["pi_opt"], state["pi"])
        pi = optax.apply_updates(state["pi"], pi_updates)

        def a_loss(log_alpha):
            return -jnp.exp(log_alpha) * jax.lax.stop_gradient(
                self._target_entropy - entropy)

        al, a_grad = jax.value_and_grad(a_loss)(state["log_alpha"])
        a_updates, a_opt = self._a_opt.update(
            a_grad, state["a_opt"], state["log_alpha"])
        log_alpha = optax.apply_updates(state["log_alpha"], a_updates)

        tau = self._tau
        polyak = lambda t, o: jax.tree.map(  # noqa: E731
            lambda a, b: (1 - tau) * a + tau * b, t, o)
        metrics = {
            "qf_loss": qf_total, "td_loss": td_loss,
            "cql_loss": cql_loss, "policy_loss": pl,
            "alpha_loss": al, "alpha": jnp.exp(log_alpha),
            "entropy": entropy,
            "total_loss": qf_total + pl + al,
        }
        return {
            "pi": pi, "q1": qs["q1"], "q2": qs["q2"],
            "q1_t": polyak(state["q1_t"], qs["q1"]),
            "q2_t": polyak(state["q2_t"], qs["q2"]),
            "log_alpha": log_alpha,
            "pi_opt": pi_opt, "q_opt": q_opt, "a_opt": a_opt,
            "key": key,
        }, metrics


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.offline_data: Optional[Any] = None
        self.cql_alpha = 1.0
        self.cql_n_actions = 4
        self.train_batch_size = 256
        self.updates_per_step = 16
        self.evaluation_episodes = 2

    def offline(self, **kw) -> "CQLConfig":
        for k, v in kw.items():
            setattr(self, k, v)
        return self


class CQL(Trainable):
    """Offline driver: reader batches -> jitted CQL updates; optional
    env eval episodes per step (reference: cql trains from
    ``input_=dataset`` with evaluation rollouts)."""

    config_cls = CQLConfig

    @classmethod
    def get_default_config(cls) -> CQLConfig:
        return cls.config_cls(algo_class=cls)

    def __init__(self, config: Optional[CQLConfig] = None, **kw):
        if config is None:
            config = self.get_default_config()
        if isinstance(config, dict):
            base = self.get_default_config()
            for k, v in config.items():
                setattr(base, k, v)
            config = base
        self._algo_config = config
        super().__init__(config.to_dict())

    def setup(self, _cfg: Dict) -> None:
        cfg = self.config = self._algo_config
        if not cfg.offline_data:
            raise ValueError("CQL requires config.offline_data "
                             "(a JSON-lines dataset path)")
        if not cfg.env:
            raise ValueError("CQL needs config.env to derive the "
                             "observation/action spaces (and for "
                             "evaluation rollouts)")
        self._env_creator = _resolve_env_creator(cfg.env, cfg.env_config)
        probe = self._env_creator()
        self.module_spec = spec_for_spaces(
            probe.observation_space, probe.action_space,
            cfg.model.get("fcnet_hiddens", (64, 64)),
            dist_for_box="squashed_gaussian")
        try:
            probe.close()
        except Exception:
            pass
        if not self.module_spec.is_continuous:
            raise ValueError("CQL is a continuous-control algorithm "
                             "(Box action spaces)")
        self.reader = JsonReader(cfg.offline_data, seed=cfg.seed)
        self.learner = CQLLearner(
            self.module_spec, cql_alpha=cfg.cql_alpha,
            cql_n_actions=cfg.cql_n_actions, actor_lr=cfg.lr,
            critic_lr=cfg.critic_lr, alpha_lr=cfg.alpha_lr,
            gamma=cfg.gamma, tau=cfg.tau,
            target_entropy=cfg.target_entropy, grad_clip=cfg.grad_clip,
            seed=cfg.seed)
        self._timesteps = 0
        low = np.asarray(self.module_spec.action_low, np.float32)
        high = np.asarray(self.module_spec.action_high, np.float32)
        self._center, self._scale = (low + high) / 2, (high - low) / 2

    def compute_single_action(self, obs: np.ndarray):
        import jax.numpy as jnp
        from ray_tpu.rllib.models import relu_mlp_forward
        out = relu_mlp_forward(self.learner.get_weights(),
                               jnp.asarray(obs[None], jnp.float32))
        mean = np.asarray(jnp.split(out, 2, axis=-1)[0][0])
        return self._center + self._scale * np.tanh(mean)

    def _eval_episodes(self, n: int) -> List[float]:
        returns = []
        env = self._env_creator()
        try:
            for i in range(n):
                out = env.reset(seed=self.config.seed * 1000 + i)
                obs = out[0] if isinstance(out, tuple) else out
                done, total = False, 0.0
                while not done:
                    step = env.step(self.compute_single_action(
                        np.asarray(obs, np.float32)))
                    if len(step) == 5:
                        obs, r, term, trunc, _ = step
                        done = term or trunc
                    else:
                        obs, r, done, _ = step
                    total += float(r)
                returns.append(total)
        finally:
            try:
                env.close()
            except Exception:
                pass
        return returns

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        metrics: Dict[str, float] = {}
        k = cfg.updates_per_step
        if k > 0:
            stacked = {key: [] for key in
                       ("obs", "next_obs", "actions", "rewards",
                        "dones")}
            for _ in range(k):
                batch = self.reader.sample(cfg.train_batch_size)
                for key in stacked:
                    stacked[key].append(batch[key].astype(np.float32))
            metrics = self.learner.update_many(
                {key: np.stack(v) for key, v in stacked.items()})
            self._timesteps += cfg.train_batch_size * k
        result = {"learner": metrics,
                  "num_env_steps_sampled_lifetime": self._timesteps}
        if cfg.evaluation_episodes:
            rets = self._eval_episodes(cfg.evaluation_episodes)
            result["episode_return_mean"] = float(np.mean(rets))
            result["episode_reward_mean"] = result["episode_return_mean"]
        return result

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "wb") as f:
            pickle.dump({"state": self.learner._state,
                         "timesteps": self._timesteps}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "rb") as f:
            blob = pickle.load(f)
        self.learner._state = blob["state"]
        self._timesteps = blob["timesteps"]

    def cleanup(self) -> None:
        pass
