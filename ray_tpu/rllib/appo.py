"""APPO: asynchronous PPO — PPO's clipped surrogate on IMPALA's
decoupled sampling with V-trace off-policy correction.

Reference: ``rllib/algorithms/appo/appo.py`` (APPOConfig: vtrace=True,
clip_param, use_kl_loss/kl_coeff/kl_target, target network updated
every ``target_update_frequency``) and the loss in
``appo/appo_learner.py`` + ``appo/torch/appo_torch_learner.py``
(surrogate clip over V-trace pg advantages, value loss against vs
targets, entropy bonus, KL regularizer toward the behaviour policy).
TPU-native shape: the V-trace recursion and the clipped update fuse
into one jitted XLA program (see impala.py); staleness between the
learner policy and the sampling policy is the async part — weights
broadcast every ``broadcast_interval`` iterations and the importance
ratios correct the drift.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, vtrace_returns


def appo_loss(fwd_out: Dict[str, jnp.ndarray],
              batch: Dict[str, jnp.ndarray], *,
              rollout_len: int = 40,
              gamma: float = 0.99,
              clip_param: float = 0.2,
              vf_loss_coeff: float = 0.5,
              entropy_coeff: float = 0.01,
              kl_coeff: float = 0.0,
              rho_clip: float = 1.0,
              c_clip: float = 1.0):
    T = rollout_len
    logits = fwd_out["action_logits"]          # [T*B, A] time-major
    values_flat = fwd_out["vf_preds"]          # [T*B]
    B = logits.shape[0] // T

    logp_all = jax.nn.log_softmax(logits)
    logp_act = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]

    tb = lambda x: x.reshape(T, B)  # noqa: E731
    target_logp = tb(logp_act)
    behavior_logp = tb(batch["behavior_logp"])
    values = tb(values_flat)
    rewards = tb(batch["rewards"])
    dones = tb(batch["dones"])
    bootstrap = batch["bootstrap_value"]       # [B]

    vs, pg_adv = vtrace_returns(
        target_logp, behavior_logp, rewards, values, bootstrap, dones,
        gamma, rho_clip, c_clip)
    adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

    # PPO clip on the off-policy ratio (reference: appo_learner computes
    # logp_ratio against the BEHAVIOUR policy when vtrace is on)
    ratio = jnp.exp(target_logp - behavior_logp)
    surr1 = ratio * adv
    surr2 = jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
    policy_loss = -jnp.mean(jnp.minimum(surr1, surr2))

    vf_loss = 0.5 * jnp.mean(jnp.square(vs - values))
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
    # KL(behaviour ‖ target) estimator over sampled actions: restrains
    # the update from straying far from the sampling policy
    mean_kl = jnp.mean(behavior_logp - target_logp)

    total = policy_loss + vf_loss_coeff * vf_loss \
        - entropy_coeff * entropy + kl_coeff * mean_kl
    metrics = {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "mean_kl": mean_kl,
        "mean_rho": jnp.mean(ratio),
    }
    return total, metrics


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param: float = 0.2
        self.use_kl_loss: bool = False
        self.kl_coeff: float = 0.2
        self.lr = 5e-4
        #: APPO default broadcast is less frequent than IMPALA's — the
        #: clip + vtrace tolerate staler batches (reference default
        #: target_update_frequency=1 with async sampling)
        self.broadcast_interval: int = 2


class APPO(IMPALA):
    config_cls = APPOConfig

    def loss_fn(self):
        return appo_loss

    def loss_config(self) -> Dict[str, Any]:
        c = self.config
        return {
            "rollout_len": c.rollout_len,
            "gamma": c.gamma,
            "clip_param": c.clip_param,
            "vf_loss_coeff": c.vf_loss_coeff,
            "entropy_coeff": c.entropy_coeff,
            "kl_coeff": c.kl_coeff if c.use_kl_loss else 0.0,
            "rho_clip": c.vtrace_rho_clip,
            "c_clip": c.vtrace_c_clip,
        }
