"""Off-policy evaluation: estimate a target policy's value from
behavior data without running it in the environment.

Reference: ``rllib/offline/estimators/`` —
``importance_sampling.py`` (IS), ``weighted_importance_sampling.py``
(WIS), ``direct_method.py`` (DM over a fitted-Q model) and
``doubly_robust.py`` (DR). Estimators consume episode-structured
batches carrying behavior action log-probs (``logp``) and a
``target_logp_fn(obs, actions) -> logp`` for the evaluated policy. The
FQE model behind DM/DR is a small jitted TD-regression, consistent with
the jitted learner stack everywhere else in this rllib.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np


def split_episodes(batch: Dict[str, np.ndarray]) -> List[Dict[str, np.ndarray]]:
    """Split a flat step batch into episodes at done=1 boundaries."""
    dones = np.asarray(batch["dones"]).astype(bool)
    out = []
    start = 0
    for t, d in enumerate(dones):
        if d:
            out.append({k: np.asarray(v)[start:t + 1]
                        for k, v in batch.items()})
            start = t + 1
    if start < len(dones):
        out.append({k: np.asarray(v)[start:]
                    for k, v in batch.items()})
    return [e for e in out if len(e["obs"])]


def _episode_weights(ep: Dict[str, np.ndarray], target_logp_fn) -> np.ndarray:
    """Cumulative importance ratios w_t = prod_{i<=t} pi(a|s)/b(a|s)."""
    tlogp = np.asarray(target_logp_fn(ep["obs"], ep["actions"]),
                       np.float64)
    blogp = np.asarray(ep["logp"], np.float64)
    # clip per-step log-ratios: one pathological step otherwise blows
    # the product past float range (reference clips ratios similarly)
    step = np.clip(tlogp - blogp, -20.0, 20.0)
    return np.exp(np.cumsum(step))


class ImportanceSampling:
    """Per-step IS (reference: importance_sampling.py): V = E over
    episodes of sum_t gamma^t w_t r_t."""

    def __init__(self, target_logp_fn: Callable, gamma: float = 0.99):
        self.target_logp_fn = target_logp_fn
        self.gamma = gamma

    def estimate(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        vals, behavior = [], []
        for ep in split_episodes(batch):
            w = _episode_weights(ep, self.target_logp_fn)
            g = self.gamma ** np.arange(len(w))
            r = np.asarray(ep["rewards"], np.float64)
            vals.append(float(np.sum(g * w * r)))
            behavior.append(float(np.sum(g * r)))
        return {"v_target": float(np.mean(vals)),
                "v_behavior": float(np.mean(behavior)),
                "num_episodes": len(vals)}


class WeightedImportanceSampling:
    """Per-step WIS (reference: weighted_importance_sampling.py):
    ratios are normalized by their per-timestep mean across episodes —
    biased but far lower variance than IS."""

    def __init__(self, target_logp_fn: Callable, gamma: float = 0.99):
        self.target_logp_fn = target_logp_fn
        self.gamma = gamma

    def estimate(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        eps = split_episodes(batch)
        ws = [_episode_weights(ep, self.target_logp_fn) for ep in eps]
        T = max((len(w) for w in ws), default=0)
        # mean cumulative ratio at each t over episodes still running
        norm = np.zeros(T)
        cnt = np.zeros(T)
        for w in ws:
            norm[:len(w)] += w
            cnt[:len(w)] += 1
        norm = norm / np.maximum(cnt, 1)
        vals, behavior = [], []
        for ep, w in zip(eps, ws):
            g = self.gamma ** np.arange(len(w))
            r = np.asarray(ep["rewards"], np.float64)
            wn = w / np.maximum(norm[:len(w)], 1e-12)
            vals.append(float(np.sum(g * wn * r)))
            behavior.append(float(np.sum(g * r)))
        return {"v_target": float(np.mean(vals)),
                "v_behavior": float(np.mean(behavior)),
                "num_episodes": len(vals)}


class FQEModel:
    """Fitted Q evaluation (reference: ``fqe_torch_model.py``): a small
    Q(s, .) MLP trained by TD toward the TARGET policy's next-action
    expectation — one jitted update."""

    def __init__(self, obs_dim: int, num_actions: int,
                 target_probs_fn: Callable, gamma: float = 0.99,
                 lr: float = 1e-3, hiddens=(64, 64), seed: int = 0):
        import jax
        import optax
        from ray_tpu.rllib.models import init_mlp
        self.num_actions = num_actions
        self.target_probs_fn = target_probs_fn
        self.gamma = gamma
        self._opt = optax.adam(lr)
        self._params = init_mlp(
            jax.random.PRNGKey(seed),
            [obs_dim, *hiddens, num_actions])
        self._opt_state = self._opt.init(self._params)
        self._jit_step = jax.jit(self._step)

    def _step(self, params, opt_state, batch):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.rllib.models import mlp_forward

        def loss(p):
            q = mlp_forward(p, batch["obs"])
            q_sa = q[jnp.arange(q.shape[0]), batch["actions"]]
            q_next = mlp_forward(p, batch["next_obs"])
            v_next = jnp.sum(batch["next_probs"] * q_next, axis=-1)
            y = batch["rewards"] + self.gamma \
                * (1.0 - batch["dones"]) * jax.lax.stop_gradient(v_next)
            return jnp.mean((q_sa - y) ** 2)

        l, grads = jax.value_and_grad(loss)(params)
        updates, opt_state = self._opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    def train(self, batch: Dict[str, np.ndarray], iters: int = 200,
              minibatch: int = 256, seed: int = 0) -> float:
        import jax.numpy as jnp
        rng = np.random.default_rng(seed)
        n = len(batch["obs"])
        next_probs = np.asarray(
            self.target_probs_fn(batch["next_obs"]), np.float32)
        loss = 0.0
        for _ in range(iters):
            idx = rng.integers(0, n, size=min(minibatch, n))
            jb = {
                "obs": jnp.asarray(batch["obs"][idx], jnp.float32),
                "next_obs": jnp.asarray(batch["next_obs"][idx],
                                        jnp.float32),
                "actions": jnp.asarray(batch["actions"][idx]),
                "rewards": jnp.asarray(batch["rewards"][idx],
                                       jnp.float32),
                "dones": jnp.asarray(batch["dones"][idx], jnp.float32),
                "next_probs": jnp.asarray(next_probs[idx]),
            }
            self._params, self._opt_state, l = self._jit_step(
                self._params, self._opt_state, jb)
            loss = float(l)
        return loss

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        from ray_tpu.rllib.models import mlp_forward
        return np.asarray(mlp_forward(
            self._params, jnp.asarray(obs, jnp.float32)))

    def v_values(self, obs: np.ndarray) -> np.ndarray:
        probs = np.asarray(self.target_probs_fn(obs), np.float64)
        return np.sum(probs * self.q_values(obs), axis=-1)


class DirectMethod:
    """DM (reference: direct_method.py): V = E[ V_FQE(s_0) ]."""

    def __init__(self, fqe: FQEModel):
        self.fqe = fqe

    def estimate(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        eps = split_episodes(batch)
        v0 = [float(self.fqe.v_values(ep["obs"][:1])[0]) for ep in eps]
        return {"v_target": float(np.mean(v0)),
                "num_episodes": len(v0)}


class DoublyRobust:
    """DR (reference: doubly_robust.py): the DM baseline plus the
    importance-weighted TD correction — unbiased like IS, low-variance
    like DM."""

    def __init__(self, fqe: FQEModel, target_logp_fn: Callable,
                 gamma: float = 0.99):
        self.fqe = fqe
        self.target_logp_fn = target_logp_fn
        self.gamma = gamma

    def estimate(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        vals = []
        for ep in split_episodes(batch):
            obs = np.asarray(ep["obs"], np.float64)
            acts = np.asarray(ep["actions"])
            r = np.asarray(ep["rewards"], np.float64)
            T = len(r)
            w = _episode_weights(ep, self.target_logp_fn)
            w_prev = np.concatenate([[1.0], w[:-1]])
            q = self.fqe.q_values(ep["obs"])
            q_sa = q[np.arange(T), acts]
            v = self.fqe.v_values(ep["obs"])
            # bootstrap from the actual next states: a truncated
            # trailing episode's final step must use V(s_{T+1}), not 0
            # (the batch carries next_obs; dones zeroes the terminal
            # case below either way)
            v_next = self.fqe.v_values(ep["next_obs"])
            dones = np.asarray(ep["dones"], np.float64)
            g = self.gamma ** np.arange(T)
            correction = w * (r + self.gamma * (1 - dones) * v_next
                              - q_sa)
            vals.append(float(v[0] + np.sum(g * correction)))
        return {"v_target": float(np.mean(vals)),
                "num_episodes": len(vals)}
