"""RLModule: the policy abstraction shared by learner and env-runners.

Reference: ``rllib/core/rl_module/rl_module.py`` —
``forward_inference`` / ``forward_exploration`` / ``forward_train``
over one parameter pytree. The train forward runs under ``jax.jit``
inside the Learner; the exploration forward runs as plain numpy-in /
numpy-out on CPU env-runner actors (no device requirement there).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.models import (
    actor_critic_forward, diag_gaussian_logp,
    gaussian_actor_critic_forward, init_actor_critic,
    init_gaussian_actor_critic)


@dataclasses.dataclass
class RLModuleSpec:
    observation_dim: int
    num_actions: int = 0
    hiddens: tuple = (64, 64)
    #: "categorical" (Discrete) or "gaussian" (Box — diagonal Gaussian,
    #: unsquashed; the env-runner clips to the space bounds like the
    #: reference's TorchDiagGaussian + action clipping)
    dist: str = "categorical"
    action_dim: int = 0
    action_low: tuple = ()
    action_high: tuple = ()

    @property
    def is_continuous(self) -> bool:
        return self.dist != "categorical"

    def build(self) -> "RLModule":
        return RLModule(self)


class RLModule:
    def __init__(self, spec: RLModuleSpec):
        self.spec = spec
        self._jit_infer = jax.jit(
            self._infer_gaussian if spec.is_continuous else self._infer)

    def init(self, key) -> Dict:
        if self.spec.is_continuous:
            return init_gaussian_actor_critic(
                key, self.spec.observation_dim, self.spec.action_dim,
                self.spec.hiddens)
        return init_actor_critic(
            key, self.spec.observation_dim, self.spec.num_actions,
            self.spec.hiddens)

    # -- train path (used inside the jitted learner update) -----------
    def forward_train(self, params: Dict, obs: jnp.ndarray
                      ) -> Dict[str, jnp.ndarray]:
        if self.spec.is_continuous:
            mean, log_std, value = gaussian_actor_critic_forward(
                params, obs)
            return {"action_mean": mean, "action_log_std": log_std,
                    "vf_preds": value}
        logits, value = actor_critic_forward(params, obs)
        return {"action_logits": logits, "vf_preds": value}

    # -- rollout path --------------------------------------------------
    @staticmethod
    def _infer(params, obs, key):
        logits, value = actor_critic_forward(params, obs)
        action = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), action]
        return action, logp, value

    @staticmethod
    def _infer_gaussian(params, obs, key):
        mean, log_std, value = gaussian_actor_critic_forward(params, obs)
        action = mean + jnp.exp(log_std) * jax.random.normal(
            key, mean.shape, mean.dtype)
        logp = diag_gaussian_logp(mean, log_std, action)
        return action, logp, value

    def forward_exploration(self, params: Dict, obs: np.ndarray,
                            key) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
        action, logp, value = self._jit_infer(
            params, jnp.asarray(obs, jnp.float32), key)
        return (np.asarray(action), np.asarray(logp), np.asarray(value))

    def forward_inference(self, params: Dict, obs: np.ndarray
                          ) -> np.ndarray:
        if self.spec.is_continuous:
            mean, _, _ = gaussian_actor_critic_forward(
                params, jnp.asarray(obs, jnp.float32))
            return np.clip(np.asarray(mean),
                           np.asarray(self.spec.action_low, np.float32),
                           np.asarray(self.spec.action_high, np.float32))
        logits, _ = actor_critic_forward(
            params, jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(logits, axis=-1))
