"""PPO: clipped-surrogate policy optimization.

Reference: ``rllib/algorithms/ppo/ppo.py`` + the torch loss in
``ppo/torch/ppo_torch_learner.py`` — clip objective, value-function
loss with clipping, entropy bonus, all under one ``jax.jit`` here.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithm_config import AlgorithmConfig


def ppo_loss(fwd_out: Dict[str, jnp.ndarray],
             batch: Dict[str, jnp.ndarray], *,
             clip_param: float = 0.2,
             vf_loss_coeff: float = 0.5,
             entropy_coeff: float = 0.0,
             vf_clip_param: float = 10.0):
    values = fwd_out["vf_preds"]
    if "action_mean" in fwd_out:
        # Box space: diagonal Gaussian (reference: TorchDiagGaussian in
        # ppo_torch_learner — same clip objective over continuous logp)
        from ray_tpu.rllib.models import (diag_gaussian_entropy,
                                          diag_gaussian_logp)
        mean = fwd_out["action_mean"]
        log_std = fwd_out["action_log_std"]
        logp = diag_gaussian_logp(mean, log_std, batch["actions"])
        entropy = jnp.mean(diag_gaussian_entropy(log_std))
    else:
        logits = fwd_out["action_logits"]
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))

    adv = batch["advantages"]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)

    ratio = jnp.exp(logp - batch["logp"])
    surr1 = ratio * adv
    surr2 = jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
    policy_loss = -jnp.mean(jnp.minimum(surr1, surr2))

    vf_err = jnp.square(values - batch["value_targets"])
    vf_loss = jnp.mean(jnp.clip(vf_err, 0.0, vf_clip_param ** 2))

    total = policy_loss + vf_loss_coeff * vf_loss \
        - entropy_coeff * entropy
    metrics = {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "mean_kl": jnp.mean(batch["logp"] - logp),
    }
    return total, metrics


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.0
        self.vf_clip_param: float = 10.0
        self.lambda_: float = 0.95
        self.lr = 5e-5
        self.num_epochs = 8
        self.minibatch_size = 128


class PPO(Algorithm):
    config_cls = PPOConfig
    supports_continuous = True

    def loss_fn(self):
        return ppo_loss

    def loss_config(self) -> Dict[str, Any]:
        c = self.config
        return {
            "clip_param": c.clip_param,
            "vf_loss_coeff": c.vf_loss_coeff,
            "entropy_coeff": c.entropy_coeff,
            "vf_clip_param": c.vf_clip_param,
        }
