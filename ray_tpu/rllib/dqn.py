"""DQN: off-policy Q-learning with replay + target network.

Reference: ``rllib/algorithms/dqn/dqn.py`` (replay-buffer training loop,
target-network sync every ``target_network_update_freq``) and the torch
loss in ``dqn/torch/dqn_torch_learner.py`` (Huber TD error, optional
double-Q). TPU-native: the whole update — Q forward, double-Q target,
Huber loss, adam, and the periodic target sync — is ONE jitted function
(the sync is a ``lax.cond`` on the step counter, so there is no
recompile and no host round-trip mid-train).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, _resolve_env_creator
from ray_tpu.rllib.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.rl_module import RLModuleSpec


class ReplayBuffer:
    """Uniform ring buffer (reference:
    ``rllib/utils/replay_buffers/replay_buffer.py``)."""

    def __init__(self, capacity: int, obs_shape, seed: int = 0,
                 action_shape=(), action_dtype=np.int64):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self.obs = np.zeros((capacity,) + tuple(obs_shape), np.float32)
        self.next_obs = np.zeros_like(self.obs)
        self.actions = np.zeros((capacity,) + tuple(action_shape),
                                action_dtype)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self._idx = 0
        self._size = 0

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(batch["obs"])
        idx = (self._idx + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=n)
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx], "rewards": self.rewards[idx],
                "dones": self.dones[idx]}

    def sample_many(self, k: int, n: int) -> Dict[str, np.ndarray]:
        """K independent minibatches stacked [K, n, ...] — feeds the
        learners' scanned multi-update (one XLA dispatch for a whole
        update burst instead of K)."""
        idx = self._rng.integers(0, self._size, size=(k, n))
        return {"obs": self.obs[idx], "next_obs": self.next_obs[idx],
                "actions": self.actions[idx],
                "rewards": self.rewards[idx], "dones": self.dones[idx]}

    def __len__(self) -> int:
        return self._size


class DQNEnvRunner:
    """Collects (s, a, r, s', done) transitions with epsilon-greedy
    exploration over the Q-network (reference: DQN's EnvRunner +
    EpsilonGreedy exploration)."""

    def __init__(self, env_creator: Callable[[], Any],
                 module_spec: RLModuleSpec, num_envs: int = 1,
                 seed: int = 0, worker_index: int = 0):
        self._envs = [env_creator() for _ in range(num_envs)]
        self._module = module_spec.build()
        self._params = None
        self._rng = np.random.default_rng(seed * 9973 + worker_index)
        self._obs = np.stack([self._reset(e, seed + i)
                              for i, e in enumerate(self._envs)])
        self._ep_returns = [0.0] * num_envs
        self._completed: List[float] = []

    @staticmethod
    def _reset(env, seed=None):
        out = env.reset(seed=seed)
        return out[0] if isinstance(out, tuple) else out

    def set_weights(self, params) -> None:
        self._params = params

    def ping(self) -> bool:
        return True

    # --- hooks the continuous SAC runner overrides --------------------
    def _make_act_buf(self, shape) -> np.ndarray:
        return np.zeros(shape, np.int64)

    def _select_actions(self, epsilon: float) -> np.ndarray:
        greedy = self._module.forward_inference(self._params, self._obs)
        n_envs = len(self._envs)
        explore = self._rng.random(n_envs) < epsilon
        random_a = self._rng.integers(
            0, self._module.spec.num_actions, size=n_envs)
        return np.where(explore, random_a, greedy)

    def _env_action(self, action):
        return int(action)

    def sample(self, num_steps: int, epsilon: float
               ) -> Dict[str, np.ndarray]:
        assert self._params is not None, "set_weights first"
        n_envs = len(self._envs)
        shape = (num_steps, n_envs)
        obs_buf = np.zeros(shape + self._obs.shape[1:], np.float32)
        next_buf = np.zeros_like(obs_buf)
        act_buf = self._make_act_buf(shape)
        rew_buf = np.zeros(shape, np.float32)
        done_buf = np.zeros(shape, np.float32)
        for t in range(num_steps):
            actions = self._select_actions(epsilon)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            for i, env in enumerate(self._envs):
                out = env.step(self._env_action(actions[i]))
                if len(out) == 5:
                    obs, rew, terminated, truncated, _ = out
                    done = terminated or truncated
                else:
                    obs, rew, done, _ = out
                    terminated = done
                rew_buf[t, i] = rew
                # bootstrap mask: only TERMINATION zeroes the next-state
                # value. A time-limit truncation is not a terminal state
                # — treating it as one biases every Q/V target at the
                # boundary (on Pendulum, the ONLY episode end is
                # truncation, which sank SAC below its learning bar)
                done_buf[t, i] = float(terminated)
                next_buf[t, i] = obs
                self._ep_returns[i] += float(rew)
                if done:
                    self._completed.append(self._ep_returns[i])
                    self._ep_returns[i] = 0.0
                    obs = self._reset(env)
                self._obs[i] = obs

        flat = lambda a: a.reshape((num_steps * n_envs,) + a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_buf), "next_obs": flat(next_buf),
                "actions": flat(act_buf), "rewards": flat(rew_buf),
                "dones": flat(done_buf)}

    def episode_returns(self, clear: bool = True) -> list:
        out = list(self._completed)
        if clear:
            self._completed = []
        return out


class DQNLearner:
    """Q-network + target network + adam, one jitted update including
    the conditional target sync (reference: DQNTorchLearner loss +
    ``target_network_update_freq``)."""

    def __init__(self, module_spec: RLModuleSpec, *, learning_rate: float,
                 gamma: float, grad_clip: Optional[float],
                 target_update_freq: int, double_q: bool, seed: int):
        import jax
        import optax
        self.module = module_spec.build()
        self._gamma = gamma
        self._double_q = double_q
        self._target_every = max(1, target_update_freq)
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        tx.append(optax.adam(learning_rate))
        self._opt = optax.chain(*tx)
        params = self.module.init(jax.random.PRNGKey(seed))
        self._state = {
            "params": params,
            "target_params": jax.tree.map(lambda x: x.copy(), params),
            "opt_state": self._opt.init(params),
            "steps": jax.numpy.zeros((), jax.numpy.int32),
        }
        self._jit_update = jax.jit(self._update, donate_argnums=(0,))

    def _q_values(self, params, obs):
        return self.module.forward_train(params, obs)["action_logits"]

    def _update(self, state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        def loss(params):
            q = self._q_values(params, batch["obs"])
            q_sa = q[jnp.arange(q.shape[0]), batch["actions"]]
            q_next_target = self._q_values(
                state["target_params"], batch["next_obs"])
            if self._double_q:
                # double-Q: online net picks, target net evaluates
                sel = jnp.argmax(
                    self._q_values(params, batch["next_obs"]), axis=-1)
                q_next = q_next_target[
                    jnp.arange(sel.shape[0]), sel]
            else:
                q_next = jnp.max(q_next_target, axis=-1)
            target = batch["rewards"] + self._gamma \
                * (1.0 - batch["dones"]) * jax.lax.stop_gradient(q_next)
            td = q_sa - target
            huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                              jnp.abs(td) - 0.5)
            return jnp.mean(huber), {
                "qf_loss": jnp.mean(huber),
                "qf_mean": jnp.mean(q_sa),
                "td_error_abs": jnp.mean(jnp.abs(td)),
            }

        (loss_val, metrics), grads = jax.value_and_grad(
            loss, has_aux=True)(state["params"])
        updates, opt_state = self._opt.update(
            grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        steps = state["steps"] + 1
        target = jax.lax.cond(
            steps % self._target_every == 0,
            lambda: params,
            lambda: state["target_params"])
        metrics = dict(metrics, total_loss=loss_val,
                       grad_norm=optax.global_norm(grads))
        return {"params": params, "target_params": target,
                "opt_state": opt_state, "steps": steps}, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self._state, metrics = self._jit_update(self._state, jb)
        return {k: float(v) for k, v in metrics.items()}

    def update_many(self, batches: Dict[str, np.ndarray]
                    ) -> Dict[str, float]:
        return _scanned_update(self, batches)

    def get_weights(self):
        return self._state["params"]


def _scanned_update(learner, batches: Dict[str, np.ndarray]
                    ) -> Dict[str, float]:
    """Run K minibatch updates as ONE jitted ``lax.scan`` over stacked
    [K, B, ...] batches (TPU-native: an off-policy train step is K tiny
    programs host-dispatched back-to-back otherwise — the scan turns
    the whole update burst into a single XLA program). Shared by the
    DQN-skeleton learners (DQN / SAC / DDPG / TD3 / CQL). Returns the
    LAST update's metrics, matching the sequential loop it replaces."""
    import jax
    import jax.numpy as jnp
    jit = getattr(learner, "_jit_update_many", None)
    if jit is None:
        def _many(state, stacked):
            def body(st, b):
                return learner._update(st, b)
            return jax.lax.scan(body, state, stacked)
        jit = learner._jit_update_many = jax.jit(
            _many, donate_argnums=(0,))
    jb = {k: jnp.asarray(v) for k, v in batches.items()}
    learner._state, metrics = jit(learner._state, jb)
    return {k: float(v[-1]) for k, v in metrics.items()}


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.gamma = 0.99
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1_000
        self.rollout_fragment_length = 4
        self.target_network_update_freq = 500   # learner updates
        self.double_q = True
        self.epsilon = [(0, 1.0), (10_000, 0.05)]  # linear schedule
        self.updates_per_step = 8

    def training(self, **kwargs) -> "DQNConfig":
        for k, v in kwargs.items():
            setattr(self, k, v)
        return self


class DQN(Algorithm):
    config_cls = DQNConfig

    #: SAC overrides: Box action spaces need a Gaussian policy, which
    #: plain Q-learning does not have
    supports_continuous = False

    def setup(self, _cfg: Dict) -> None:
        from ray_tpu.rllib.algorithm import spec_for_spaces
        cfg = self.config = self._algo_config
        env_creator = _resolve_env_creator(cfg.env, cfg.env_config)
        probe = env_creator()
        obs_shape = probe.observation_space.shape
        self.module_spec = spec_for_spaces(
            probe.observation_space, probe.action_space,
            cfg.model.get("fcnet_hiddens", (64, 64)),
            dist_for_box="squashed_gaussian")
        if self.module_spec.is_continuous and not self.supports_continuous:
            raise ValueError(
                f"{type(self).__name__} supports Discrete action spaces "
                f"only; use SAC for Box spaces")
        try:
            probe.close()
        except Exception:
            pass
        self.learner = self._make_learner()
        if self.module_spec.is_continuous:
            self.buffer = ReplayBuffer(
                cfg.replay_buffer_capacity, obs_shape, seed=cfg.seed,
                action_shape=(self.module_spec.action_dim,),
                action_dtype=np.float32)
        else:
            self.buffer = ReplayBuffer(
                cfg.replay_buffer_capacity, obs_shape, seed=cfg.seed)
        n_runners = max(1, cfg.num_env_runners)
        runner_cls = ray_tpu.remote(num_cpus=1)(self._runner_cls())
        self.env_runners = [
            runner_cls.remote(env_creator, self.module_spec,
                              cfg.num_envs_per_env_runner, cfg.seed, i)
            for i in range(n_runners)]
        self._sync_weights()
        self._timesteps = 0
        self._return_window: List[float] = []

    # overridable by off-policy variants (SAC) so setup() builds the
    # right learner/runners ONCE instead of a kill-and-recreate pass
    def _make_learner(self):
        cfg = self.config
        return DQNLearner(
            self.module_spec, learning_rate=cfg.lr, gamma=cfg.gamma,
            grad_clip=cfg.grad_clip,
            target_update_freq=cfg.target_network_update_freq,
            double_q=cfg.double_q, seed=cfg.seed)

    def _runner_cls(self):
        return DQNEnvRunner

    def _sync_weights(self) -> None:
        w_ref = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([r.set_weights.remote(w_ref)
                     for r in self.env_runners])

    def _epsilon(self) -> float:
        pts = self.config.epsilon
        t = self._timesteps
        for (t0, e0), (t1, e1) in zip(pts, pts[1:]):
            if t < t1:
                frac = (t - t0) / max(1, t1 - t0)
                return float(e0 + (e1 - e0) * min(1.0, max(0.0, frac)))
        return float(pts[-1][1])

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        batches = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length, eps)
             for r in self.env_runners])
        for b in batches:
            self.buffer.add_batch(b)
            self._timesteps += len(b["obs"])

        metrics: Dict[str, float] = {}
        if self._timesteps >= cfg.num_steps_sampled_before_learning_starts:
            if cfg.updates_per_step > 1:
                metrics = self.learner.update_many(
                    self.buffer.sample_many(cfg.updates_per_step,
                                            cfg.train_batch_size))
                self._sync_weights()
            elif cfg.updates_per_step == 1:
                metrics = self.learner.update(
                    self.buffer.sample(cfg.train_batch_size))
                self._sync_weights()
            # updates_per_step == 0: collection only, no training

        returns: List[float] = []
        for r in ray_tpu.get(
                [r.episode_returns.remote() for r in self.env_runners]):
            returns.extend(r)
        self._return_window.extend(returns)
        self._return_window = self._return_window[-100:]
        mean_return = (float(np.mean(self._return_window))
                       if self._return_window else float("nan"))
        return {
            "episode_return_mean": mean_return,
            "episode_reward_mean": mean_return,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "epsilon": eps,
            "learner": metrics,
        }

    def train(self) -> Dict[str, Any]:
        result = Algorithm.train(self)
        return result

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "wb") as f:
            pickle.dump({"state": self.learner._state,
                         "timesteps": self._timesteps}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"),
                  "rb") as f:
            blob = pickle.load(f)
        self.learner._state = blob["state"]
        self._timesteps = blob["timesteps"]
        self._sync_weights()

    def get_policy_weights(self):
        return self.learner.get_weights()

    def compute_single_action(self, obs: np.ndarray) -> int:
        w = self.learner.get_weights()
        return int(self.module_spec.build().forward_inference(
            w, obs[None])[0])

    def cleanup(self) -> None:
        for r in getattr(self, "env_runners", []):
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
