"""EnvRunner: CPU actors stepping (vectorized) gymnasium envs.

Reference: ``rllib/evaluation/rollout_worker.py:159`` (``sample`` :653)
/ the new ``env/env_runner.py`` API. Runners hold the env + a numpy
copy of the policy params; ``sample()`` returns a flat rollout batch
with GAE advantages already computed, so the learner's jitted update
consumes it directly.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                dones: np.ndarray, last_value: float,
                gamma: float, lam: float):
    """Generalized advantage estimation over one rollout segment."""
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    last_gae = 0.0
    for t in reversed(range(T)):
        next_value = last_value if t == T - 1 else values[t + 1]
        non_terminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * non_terminal - values[t]
        last_gae = delta + gamma * lam * non_terminal * last_gae
        adv[t] = last_gae
    returns = adv + values
    return adv, returns


class EnvRunner:
    """One rollout actor (spawn several for parallel sampling)."""

    def __init__(self, env_creator: Callable[[], Any],
                 module_spec: RLModuleSpec, num_envs: int = 1,
                 gamma: float = 0.99, lambda_: float = 0.95,
                 seed: int = 0, worker_index: int = 0,
                 obs_connectors: Optional[list] = None):
        import jax
        from ray_tpu.rllib.connectors import ConnectorPipeline
        self._envs = [env_creator() for _ in range(num_envs)]
        self._module = module_spec.build()
        self._connectors = ConnectorPipeline(obs_connectors) \
            if obs_connectors else None
        self._params = None
        self._gamma = gamma
        self._lambda = lambda_
        self._key = jax.random.PRNGKey(seed * 10_003 + worker_index)
        self._obs = np.stack([
            self._reset(e, seed * 7919 + worker_index * 131 + i)
            for i, e in enumerate(self._envs)])
        self._cur_obs: Optional[np.ndarray] = None
        self._ep_returns = [0.0] * num_envs
        self._completed: list = []

    @staticmethod
    def _reset(env, seed=None):
        out = env.reset(seed=seed)
        return out[0] if isinstance(out, tuple) else out

    def set_weights(self, params) -> None:
        self._params = params

    def get_weights(self):
        return self._params

    def _transformed_obs(self) -> np.ndarray:
        """Connector-transformed view of the CURRENT raw obs, applied
        exactly once per distinct observation (stateful connectors —
        FrameStack, NormalizeObs — must see each obs once; re-applying
        for shape probes or bootstraps would corrupt their state)."""
        if self._cur_obs is None:
            self._cur_obs = self._connectors(self._obs) \
                if self._connectors else self._obs.astype(np.float32)
        return self._cur_obs

    def _rollout(self, num_steps: int):
        """Shared stepping loop for both sampling modes. Returns
        time-major buffers [T, B, ...] plus the bootstrap values of the
        final state."""
        import jax
        assert self._params is not None, "set_weights first"
        n_envs = len(self._envs)
        spec = self._module.spec
        continuous = spec.is_continuous
        cur0 = self._transformed_obs()
        obs_buf = np.zeros((num_steps, n_envs) + cur0.shape[1:],
                           np.float32)
        if continuous:
            act_buf = np.zeros((num_steps, n_envs, spec.action_dim),
                               np.float32)
            low = np.asarray(spec.action_low, np.float32)
            high = np.asarray(spec.action_high, np.float32)
        else:
            act_buf = np.zeros((num_steps, n_envs), np.int64)
        logp_buf = np.zeros((num_steps, n_envs), np.float32)
        val_buf = np.zeros((num_steps, n_envs), np.float32)
        rew_buf = np.zeros((num_steps, n_envs), np.float32)
        done_buf = np.zeros((num_steps, n_envs), np.float32)

        for t in range(num_steps):
            cur = self._transformed_obs()
            self._key, sub = jax.random.split(self._key)
            actions, logps, values = self._module.forward_exploration(
                self._params, cur, sub)
            obs_buf[t] = cur
            act_buf[t] = actions
            logp_buf[t] = logps
            val_buf[t] = values
            for i, env in enumerate(self._envs):
                # the stored action is the RAW sample (ratios in the
                # loss need the sampled point); the env sees it clipped
                # to the Box bounds (reference: unsquashed DiagGaussian
                # + action clipping at the env boundary)
                out = env.step(np.clip(actions[i], low, high)
                               if continuous else int(actions[i]))
                if len(out) == 5:
                    obs, rew, terminated, truncated, _ = out
                    done = terminated or truncated
                else:  # old gym API
                    obs, rew, done, _ = out
                rew_buf[t, i] = rew
                done_buf[t, i] = float(done)
                self._ep_returns[i] += float(rew)
                if done:
                    self._completed.append(self._ep_returns[i])
                    self._ep_returns[i] = 0.0
                    obs = self._reset(env)
                self._obs[i] = obs
            self._cur_obs = None  # raw obs changed

        # bootstrap values of the final state (the transform is cached,
        # so the next rollout's t=0 reuses it — still one application)
        self._key, sub = jax.random.split(self._key)
        _, _, last_values = self._module.forward_exploration(
            self._params, self._transformed_obs(), sub)
        return (obs_buf, act_buf, logp_buf, val_buf, rew_buf, done_buf,
                np.asarray(last_values, np.float32))

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect num_steps per env; returns the flattened batch with
        GAE advantages."""
        (obs_buf, act_buf, logp_buf, val_buf, rew_buf, done_buf,
         last_values) = self._rollout(num_steps)
        n_envs = len(self._envs)
        adv = np.zeros_like(rew_buf)
        ret = np.zeros_like(rew_buf)
        for i in range(n_envs):
            adv[:, i], ret[:, i] = compute_gae(
                rew_buf[:, i], val_buf[:, i], done_buf[:, i],
                float(last_values[i]), self._gamma, self._lambda)

        flat = lambda arr: arr.reshape(  # noqa: E731
            (num_steps * n_envs,) + arr.shape[2:])
        return {
            "obs": flat(obs_buf),
            "actions": flat(act_buf),
            "logp": flat(logp_buf),
            "value_targets": flat(ret),
            "advantages": flat(adv),
        }

    def sample_blocks(self, num_blocks: int, steps_per_block: int
                      ) -> "Any":
        """Generator of ``num_blocks`` consecutive rollout blocks of
        ``steps_per_block`` env steps each — the producer half of the
        rollout→train streaming dataflow. Works as a streaming actor
        call (``runner.sample_blocks.options(num_returns="streaming")
        .remote(...)``) on a live runner; ``rllib.rollout_stream``
        wraps the same loop in a deterministic generator TASK when
        lineage replay of the stream prefix is required."""
        for _ in range(num_blocks):
            yield self.sample(steps_per_block)

    def sample_segments(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Time-major rollout segments for off-policy correction
        (IMPALA/V-trace needs the [T, B] structure + behavior log-probs
        + the bootstrap value of the final state; GAE is NOT computed —
        the learner's V-trace recursion replaces it)."""
        (obs_buf, act_buf, logp_buf, _val, rew_buf, done_buf,
         last_values) = self._rollout(num_steps)
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "behavior_logp": logp_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "bootstrap_value": last_values,
        }

    def episode_returns(self, clear: bool = True) -> list:
        out = list(self._completed)
        if clear:
            self._completed = []
        return out

    def ping(self) -> bool:
        return True
