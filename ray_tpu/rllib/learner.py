"""Learner + LearnerGroup: the jitted update stack.

Reference: ``rllib/core/learner/learner.py:106`` (``compute_loss``
:893, ``compute_gradients`` :454, ``apply_gradients`` :584) and
``learner_group.py:60``. TPU-first: loss+grad+apply is ONE jitted
program with donated state (the reference splits these into three torch
calls); multi-learner data parallelism shards the batch across learner
actors and averages gradients — the averaging itself is a jitted
tree-map, and on real multi-chip hosts the same Learner runs under a
dp-sharded mesh instead.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec
from ray_tpu.rllib.rollout_stream import _concat_batches, _nrows


class Learner:
    """Holds params + optimizer state; update() is one jitted step."""

    def __init__(self, module_spec: RLModuleSpec,
                 loss_fn: Callable[..., Tuple[jnp.ndarray, Dict]],
                 learning_rate: float = 3e-4,
                 grad_clip: Optional[float] = 0.5, seed: int = 0,
                 loss_config: Optional[Dict[str, Any]] = None):
        import optax
        self.module = module_spec.build()
        self._loss_fn = loss_fn
        self._loss_config = loss_config or {}
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        tx.append(optax.adam(learning_rate))
        self._opt = optax.chain(*tx)
        params = self.module.init(jax.random.PRNGKey(seed))
        self._state = {"params": params,
                       "opt_state": self._opt.init(params)}
        self._jit_update = jax.jit(self._update, donate_argnums=(0,))
        self._jit_grads = jax.jit(self._grads)

    # -- jitted core ---------------------------------------------------
    def _update(self, state, batch):
        def loss(params):
            out = self.module.forward_train(params, batch["obs"])
            return self._loss_fn(out, batch, **self._loss_config)

        import optax
        (loss_val, metrics), grads = jax.value_and_grad(
            loss, has_aux=True)(state["params"])
        updates, opt_state = self._opt.update(
            grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        metrics = dict(metrics, total_loss=loss_val,
                       grad_norm=optax.global_norm(grads))
        return {"params": params, "opt_state": opt_state}, metrics

    def _grads(self, params, batch):
        def loss(p):
            out = self.module.forward_train(p, batch["obs"])
            return self._loss_fn(out, batch, **self._loss_config)
        (loss_val, metrics), grads = jax.value_and_grad(
            loss, has_aux=True)(params)
        return grads, dict(metrics, total_loss=loss_val)

    # -- public --------------------------------------------------------
    def update_from_batch(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        self._state, metrics = self._jit_update(self._state, jbatch)
        return {k: float(v) for k, v in metrics.items()}

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        """Data-parallel path: grads only (averaged by the group)."""
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        grads, metrics = self._jit_grads(self._state["params"], jbatch)
        return grads, {k: float(v) for k, v in metrics.items()}

    def apply_gradients(self, grads) -> None:
        import optax
        updates, opt_state = self._opt.update(
            grads, self._state["opt_state"], self._state["params"])
        self._state = {
            "params": optax.apply_updates(self._state["params"], updates),
            "opt_state": opt_state}

    def get_weights(self):
        return jax.tree.map(np.asarray, self._state["params"])

    def set_weights(self, params) -> None:
        self._state["params"] = jax.tree.map(jnp.asarray, params)


class LearnerGroup:
    """Local single learner, or N remote learner actors doing
    data-parallel updates with gradient averaging
    (reference ``learner_group.py:60``, ``update_from_batch`` :202)."""

    def __init__(self, make_learner: Callable[[], Learner],
                 num_learners: int = 0,
                 resources_per_learner: Optional[Dict] = None,
                 seed: int = 0):
        self._num = num_learners
        # One generator for the whole run: minibatch permutations must
        # differ across training iterations.
        self._rng = np.random.default_rng(seed)
        if num_learners == 0:
            self._local = make_learner()
            self._remote: List[Any] = []
        else:
            self._local = None
            opts = dict(resources_per_learner or {"num_cpus": 1})
            cls = ray_tpu.remote(**opts)(_RemoteLearner)
            self._remote = [cls.remote(make_learner)
                            for _ in range(num_learners)]
            # All learners start from learner 0's weights.
            w = ray_tpu.get(self._remote[0].get_weights.remote())
            ray_tpu.get([a.set_weights.remote(w)
                         for a in self._remote[1:]])

    def update_from_batch(self, batch: Dict[str, np.ndarray],
                          minibatch_size: Optional[int] = None,
                          num_epochs: int = 1) -> Dict[str, float]:
        metrics: Dict[str, float] = {}
        n = _nrows(batch)
        mb = minibatch_size or n
        for _ in range(num_epochs):
            perm = self._rng.permutation(n)
            for start in range(0, n, mb):
                idx = perm[start:start + mb]
                sub = {k: v[idx] for k, v in batch.items()}
                metrics = self._one_update(sub)
        return metrics

    def _one_update(self, batch) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update_from_batch(batch)
        # shard batch across learners; average gradients
        shards = np.array_split(np.arange(_nrows(batch)), self._num)
        futs = [a.compute_gradients.remote(
            {k: v[idx] for k, v in batch.items()})
            for a, idx in zip(self._remote, shards) if len(idx)]
        results = ray_tpu.get(futs)
        grads = jax.tree.map(
            lambda *gs: np.mean(np.stack(gs), axis=0),
            *[g for g, _ in results])
        ray_tpu.get([a.apply_gradients.remote(grads)
                     for a in self._remote])
        return results[0][1]

    def update_from_stream(self, stream,
                           minibatch_size: Optional[int] = None,
                           num_epochs: int = 1
                           ) -> Dict[str, float]:
        """Streaming rollout→train epoch (Podracer-style): the FIRST
        epoch consumes minibatches straight off the rollout stream as
        blocks arrive (``RolloutBlockStream.iter_batches`` — the
        learner updates while runners are still sampling, no epoch
        barrier), collecting the blocks; the remaining ``num_epochs -
        1`` epochs run the usual shuffled-minibatch passes over the
        collected full batch. Streamed minibatches drop the ragged
        tail so every update shares one jitted shape."""
        stream._collect = True
        metrics: Dict[str, float] = {}
        n_updates = 0
        for mb in stream.iter_batches(minibatch_size, drop_last=True):
            metrics = self._one_update(mb)
            n_updates += 1
        if not stream.blocks:
            return metrics
        if num_epochs > 1:
            batch = stream.full_batch()
            metrics = self.update_from_batch(
                batch, minibatch_size=minibatch_size,
                num_epochs=num_epochs - 1)
        metrics = dict(metrics)
        metrics["stream_updates"] = float(n_updates)
        return metrics

    def update_from_stream_sharded(self, stream,
                                   minibatch_size: Optional[int] = None,
                                   num_epochs: int = 1,
                                   on_round: Optional[
                                       Callable[[int, Dict[str, float]],
                                                None]] = None
                                   ) -> Dict[str, float]:
        """Multi-learner streaming epoch: the FIRST epoch trains on ALL
        learners as blocks arrive (today's ``update_from_stream`` feeds
        one update at a time through the group barrier). Each arriving
        block is assigned to a learner shard deterministically — by
        ``worker_index mod num_learners``, so a lineage-replayed block
        re-chunks onto the SAME learner and, when the runner count
        divides the learner count, every shard's minibatch sequence is
        reproducible regardless of cross-runner arrival order. Each
        learner computes gradients on its own shard concurrently; a
        synchronous round closes once every learner holds a gradient,
        and the round average applies to ALL learners, keeping replicas
        identical. Ragged tails average over the learners that have
        data. Epochs 2+ run the usual shuffled passes over the
        collected full batch. ``on_round`` fires after each applied
        round — the RLHF trainer's in-flight weight-publish hook (the
        engines are still decoding when it runs). Falls back to
        ``update_from_stream`` for the local/single-learner group."""
        if self._local is not None or self._num < 2:
            return self.update_from_stream(stream, minibatch_size,
                                           num_epochs)
        stream._collect = True
        n = self._num
        per = max(1, minibatch_size // n) if minibatch_size else None
        buffers: List[List[Dict[str, np.ndarray]]] = \
            [[] for _ in range(n)]
        rows = [0] * n
        futs: List[collections.deque] = \
            [collections.deque() for _ in range(n)]
        self.shard_rows = [0] * n
        self.shard_uids: List[List[int]] = [[] for _ in range(n)]
        metrics: Dict[str, float] = {}
        n_rounds = 0

        def launch(i: int, take: int) -> None:
            merged = _concat_batches(buffers[i])
            sub = {k: v[:take] for k, v in merged.items()}
            rest = _nrows(merged) - take
            buffers[i] = [{k: v[take:] for k, v in merged.items()}] \
                if rest else []
            rows[i] = rest
            futs[i].append(
                self._remote[i].compute_gradients.remote(sub))
            self.shard_rows[i] += take

        def close_round(require_all: bool) -> bool:
            nonlocal metrics, n_rounds
            have = [i for i in range(n) if futs[i]]
            if not have or (require_all and len(have) < n):
                return False
            results = ray_tpu.get([futs[i].popleft() for i in have])
            grads = jax.tree.map(
                lambda *gs: np.mean(np.stack(gs), axis=0),
                *[g for g, _ in results])
            ray_tpu.get([a.apply_gradients.remote(grads)
                         for a in self._remote])
            metrics = results[0][1]
            n_rounds += 1
            if on_round is not None:
                on_round(n_rounds, metrics)
            return True

        for batch, info in stream.iter_blocks():
            i = int(info.get("shard_key",
                             info.get("worker_index",
                                      info.get("uid", 0)))) % n
            self.shard_uids[i].append(int(info.get("uid", -1)))
            buffers[i].append(batch)
            rows[i] += _nrows(batch)
            target = per if per is not None else rows[i]
            while target > 0 and rows[i] >= target:
                launch(i, target)
                if per is None:
                    break
            while close_round(require_all=True):
                pass
        for i in range(n):          # ragged shard tails
            if rows[i]:
                launch(i, rows[i])
        while close_round(require_all=False):
            pass
        if stream.blocks and num_epochs > 1:
            metrics = self.update_from_batch(
                stream.full_batch(), minibatch_size=minibatch_size,
                num_epochs=num_epochs - 1)
        metrics = dict(metrics)
        metrics["stream_updates"] = float(n_rounds)
        metrics["learners_used"] = float(
            sum(1 for r in self.shard_rows if r))
        return metrics

    def update_ordered(self, batch: Dict[str, np.ndarray]
                       ) -> Dict[str, float]:
        """One full-batch update with NO shuffling — sequence-structured
        losses (V-trace's [T, B] reshape) need samples in order. Remote
        multi-learner sharding would split the time axis, so ordered
        updates always run on one learner."""
        if self._local is not None:
            return self._local.update_from_batch(batch)
        return ray_tpu.get(
            self._remote[0].update_from_batch.remote(batch))

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._remote[0].get_weights.remote())

    def set_weights(self, w) -> None:
        if self._local is not None:
            self._local.set_weights(w)
        else:
            ray_tpu.get([a.set_weights.remote(w) for a in self._remote])

    def shutdown(self) -> None:
        for a in self._remote:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


class _RemoteLearner:
    """Actor wrapper (grads move as numpy pytrees)."""

    def __init__(self, make_learner):
        self._learner = make_learner()

    def compute_gradients(self, batch):
        grads, metrics = self._learner.compute_gradients(batch)
        return jax.tree.map(np.asarray, grads), metrics

    def apply_gradients(self, grads):
        self._learner.apply_gradients(
            jax.tree.map(jnp.asarray, grads))

    def get_weights(self):
        return self._learner.get_weights()

    def set_weights(self, w):
        self._learner.set_weights(w)

    def update_from_batch(self, batch):
        return self._learner.update_from_batch(batch)
