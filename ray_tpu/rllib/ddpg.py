"""DDPG and TD3: deterministic-policy-gradient continuous control.

Reference: ``rllib/algorithms/ddpg/ddpg.py`` (+ ``ddpg_torch_model.py``:
deterministic tanh actor, Q(s, a) critic, target nets, OU/Gaussian
action noise) and ``rllib/algorithms/td3/td3.py`` (DDPG + the three TD3
fixes: twin critics, delayed policy updates, target policy smoothing).
TPU-native shape, like SAC/DQN here: critic update, (possibly delayed)
actor update, and polyak syncs compile into ONE jitted XLA program per
step — the policy delay is a ``lax.cond`` on the step counter, not a
host-side branch."""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNEnvRunner
from ray_tpu.rllib.models import init_mlp, relu_mlp_forward
from ray_tpu.rllib.rl_module import RLModuleSpec
from ray_tpu.rllib.sac import SACConfig


class DDPGEnvRunner(DQNEnvRunner):
    """Rollout actor: deterministic tanh policy + Gaussian exploration
    noise, clipped back into (-1, 1) (reference: ddpg's
    GaussianNoise exploration). The replay buffer stores the noisy
    squashed action; the env sees it rescaled to the Box bounds."""

    def __init__(self, env_creator, module_spec: RLModuleSpec,
                 num_envs: int = 1, seed: int = 0,
                 worker_index: int = 0, noise_sigma: float = 0.1):
        super().__init__(env_creator, module_spec, num_envs, seed,
                         worker_index)
        self._noise_sigma = noise_sigma
        low = np.asarray(module_spec.action_low, np.float32)
        high = np.asarray(module_spec.action_high, np.float32)
        self._center = (low + high) / 2.0
        self._scale = (high - low) / 2.0

    def _make_act_buf(self, shape) -> np.ndarray:
        return np.zeros(shape + (self._module.spec.action_dim,),
                        np.float32)

    def _select_actions(self, epsilon: float) -> np.ndarray:
        import jax.numpy as jnp
        mu = np.asarray(jnp.tanh(relu_mlp_forward(
            self._params, jnp.asarray(self._obs, jnp.float32))),
            np.float32)
        noise = self._rng.normal(0.0, self._noise_sigma, mu.shape)
        return np.clip(mu + noise, -1.0, 1.0).astype(np.float32)

    def _env_action(self, action):
        return self._center + self._scale * action


class DDPGLearner:
    """Q(s, a) critic(s) + deterministic actor + targets, one jitted
    update. ``twin_q``/``policy_delay``/``smooth_target_noise`` give the
    TD3 variant (reference: td3.py sets exactly these on ddpg)."""

    def __init__(self, module_spec: RLModuleSpec, *,
                 actor_lr: float, critic_lr: float, gamma: float,
                 tau: float, grad_clip: Optional[float], seed: int,
                 twin_q: bool = False, policy_delay: int = 1,
                 smooth_target_noise: float = 0.0,
                 smooth_target_clip: float = 0.5):
        import jax
        import jax.numpy as jnp
        import optax
        self.spec = module_spec
        self._gamma = gamma
        self._tau = tau
        self._twin = twin_q
        self._delay = max(1, policy_delay)
        self._noise = smooth_target_noise
        self._noise_clip = smooth_target_clip
        adim = module_spec.action_dim
        obs_dim = module_spec.observation_dim
        h = list(module_spec.hiddens)

        def maybe_clip(tx):
            return optax.chain(optax.clip_by_global_norm(grad_clip),
                               tx) if grad_clip else tx

        self._pi_opt = maybe_clip(optax.adam(actor_lr))
        self._q_opt = maybe_clip(optax.adam(critic_lr))

        keys = jax.random.split(jax.random.PRNGKey(seed), 4)
        pi = init_mlp(keys[0], [obs_dim, *h, adim], scale=0.01)
        q_sizes = [obs_dim + adim, *h, 1]
        qs = {"q1": init_mlp(keys[1], q_sizes)}
        if twin_q:
            qs["q2"] = init_mlp(keys[2], q_sizes)
        self._state = {
            "pi": pi, "qs": qs,
            "pi_t": jax.tree.map(lambda x: x.copy(), pi),
            "qs_t": jax.tree.map(lambda x: x.copy(), qs),
            "pi_opt": self._pi_opt.init(pi),
            "q_opt": self._q_opt.init(qs),
            "steps": jnp.zeros((), jnp.int32),
            "key": keys[3],
        }
        self._jit_update = jax.jit(self._update, donate_argnums=(0,))

    @staticmethod
    def _mu(pi_params, obs):
        import jax.numpy as jnp
        return jnp.tanh(relu_mlp_forward(pi_params, obs))

    @staticmethod
    def _q(q_params, obs, act):
        import jax.numpy as jnp
        return relu_mlp_forward(q_params, jnp.concatenate([obs, act], -1)
                           )[..., 0]

    def _update(self, state, batch):
        import jax
        import jax.numpy as jnp
        import optax

        obs, next_obs = batch["obs"], batch["next_obs"]
        acts = batch["actions"]
        key, k_noise = jax.random.split(state["key"])

        # -- target action, optionally smoothed (TD3 fix #3) ----------
        a_next = self._mu(state["pi_t"], next_obs)
        if self._noise > 0.0:
            eps = jnp.clip(
                self._noise * jax.random.normal(k_noise, a_next.shape,
                                                a_next.dtype),
                -self._noise_clip, self._noise_clip)
            a_next = jnp.clip(a_next + eps, -1.0, 1.0)
        q_next = self._q(state["qs_t"]["q1"], next_obs, a_next)
        if self._twin:
            q_next = jnp.minimum(
                q_next, self._q(state["qs_t"]["q2"], next_obs, a_next))
        y = batch["rewards"] + self._gamma * (1.0 - batch["dones"]) \
            * jax.lax.stop_gradient(q_next)

        def q_loss(qs):
            l = jnp.mean((self._q(qs["q1"], obs, acts) - y) ** 2)
            if self._twin:
                l = l + jnp.mean((self._q(qs["q2"], obs, acts) - y) ** 2)
            return l

        qf_loss, q_grads = jax.value_and_grad(q_loss)(state["qs"])
        q_updates, q_opt = self._q_opt.update(
            q_grads, state["q_opt"], state["qs"])
        qs = optax.apply_updates(state["qs"], q_updates)

        # -- delayed deterministic policy gradient (TD3 fix #2) -------
        def pi_loss(pi_params):
            return -jnp.mean(self._q(qs["q1"], obs,
                                     self._mu(pi_params, obs)))

        pl, pi_grads = jax.value_and_grad(pi_loss)(state["pi"])
        pi_updates, pi_opt = self._pi_opt.update(
            pi_grads, state["pi_opt"], state["pi"])
        pi_new = optax.apply_updates(state["pi"], pi_updates)

        steps = state["steps"] + 1
        tau = self._tau
        polyak = lambda t, o: jax.tree.map(  # noqa: E731
            lambda a, b: (1 - tau) * a + tau * b, t, o)

        def do_policy():
            return (pi_new, pi_opt, polyak(state["pi_t"], pi_new))

        def skip_policy():
            return (state["pi"], state["pi_opt"], state["pi_t"])

        pi, pi_opt_out, pi_t = jax.lax.cond(
            steps % self._delay == 0, do_policy, skip_policy)

        metrics = {
            "qf_loss": qf_loss, "policy_loss": pl,
            "q_mean": jnp.mean(self._q(qs["q1"], obs, acts)),
            "total_loss": qf_loss + pl,
        }
        return {
            "pi": pi, "qs": qs,
            "pi_t": pi_t, "qs_t": polyak(state["qs_t"], qs),
            "pi_opt": pi_opt_out, "q_opt": q_opt,
            "steps": steps, "key": key,
        }, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self._state, metrics = self._jit_update(self._state, jb)
        return {k: float(v) for k, v in metrics.items()}

    def update_many(self, batches):
        from ray_tpu.rllib.dqn import _scanned_update
        return _scanned_update(self, batches)

    def get_weights(self):
        return self._state["pi"]


class DDPGConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.lr = 1e-3                 # actor
        self.critic_lr = 1e-3
        self.tau = 0.005
        self.exploration_noise = 0.1
        self.twin_q = False
        self.policy_delay = 1
        self.smooth_target_noise = 0.0
        self.smooth_target_clip = 0.5


class DDPG(DQN):
    config_cls = DDPGConfig
    supports_continuous = True

    def setup(self, _cfg: Dict) -> None:
        super().setup(_cfg)
        if not self.module_spec.is_continuous:
            raise ValueError(
                "DDPG/TD3 are continuous-control algorithms; use DQN or "
                "discrete SAC for Discrete action spaces")

    def _make_learner(self):
        cfg = self.config
        return DDPGLearner(
            self.module_spec, actor_lr=cfg.lr, critic_lr=cfg.critic_lr,
            gamma=cfg.gamma, tau=cfg.tau, grad_clip=cfg.grad_clip,
            seed=cfg.seed, twin_q=cfg.twin_q,
            policy_delay=cfg.policy_delay,
            smooth_target_noise=cfg.smooth_target_noise,
            smooth_target_clip=cfg.smooth_target_clip)

    def _runner_cls(self):
        noise = self.config.exploration_noise

        class _Runner(DDPGEnvRunner):
            def __init__(self, *a, **kw):
                super().__init__(*a, noise_sigma=noise, **kw)
        _Runner.__name__ = "DDPGEnvRunner"
        return _Runner

    def compute_single_action(self, obs: np.ndarray):
        import jax.numpy as jnp
        mu = np.asarray(jnp.tanh(relu_mlp_forward(
            self.learner.get_weights(),
            jnp.asarray(obs[None], jnp.float32))))[0]
        low = np.asarray(self.module_spec.action_low, np.float32)
        high = np.asarray(self.module_spec.action_high, np.float32)
        return (low + high) / 2.0 + (high - low) / 2.0 * mu


class TD3Config(DDPGConfig):
    """Reference: ``td3.py`` — DDPG defaults flipped to the TD3 paper's
    (twin critics, delay 2, smoothed targets, higher noise)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self.twin_q = True
        self.policy_delay = 2
        self.smooth_target_noise = 0.2
        self.smooth_target_clip = 0.5
        self.lr = 1e-3
        self.critic_lr = 1e-3


class TD3(DDPG):
    config_cls = TD3Config
