"""Multi-node simulation on one machine.

Reference: ``python/ray/cluster_utils.py:108`` — ``Cluster``/
``add_node`` start extra raylets against one GCS so distributed
scheduling/failure tests need no real cluster (SURVEY §4). Here extra
node managers run as subprocesses joining the head session.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu


class _NodeProc:
    def __init__(self, proc: subprocess.Popen, node_id_hint: str):
        self.proc = proc
        self.node_id_hint = node_id_hint

    def kill(self, sig=None) -> None:
        self.proc.kill()
        self.proc.wait(timeout=10)


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 connect: bool = False,
                 head_node_args: Optional[Dict] = None):
        self._nodes: List[_NodeProc] = []
        self._head_info = None
        self.session_dir: Optional[str] = None
        if initialize_head:
            args = dict(head_node_args or {})
            args.setdefault("num_cpus", 2)
            self._head_info = ray_tpu.init(**args)
            self.session_dir = self._head_info.get("session_dir")
            self._connected = True
        else:
            self._connected = connect

    @property
    def address(self) -> Optional[str]:
        return self.session_dir

    def add_node(self, *, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 wait: bool = True, env: Optional[Dict] = None) -> _NodeProc:
        assert self.session_dir, "head must be started first"
        before = {n["node_id"] for n in ray_tpu.nodes()}
        cmd = [sys.executable, "-m", "ray_tpu.core.node",
               "--session-dir", self.session_dir,
               "--num-cpus", str(num_cpus),
               "--resources", json.dumps(resources or {}),
               "--labels", json.dumps(labels or {}),
               "--initial-workers", "0"]
        if num_tpus:
            cmd += ["--num-tpus", str(num_tpus)]
        child_env = dict(os.environ)
        child_env.update(env or {})
        proc = subprocess.Popen(
            cmd, env=child_env,
            stdout=open(os.path.join(
                self.session_dir, "logs",
                f"node-{len(self._nodes)}.out"), "ab"),
            stderr=subprocess.STDOUT)
        node = _NodeProc(proc, "")
        self._nodes.append(node)
        if wait:
            deadline = time.time() + 30
            while time.time() < deadline:
                now = {n["node_id"] for n in ray_tpu.nodes()
                       if n["alive"]}
                new = now - before
                if new:
                    node.node_id_hint = next(iter(new))
                    return node
                time.sleep(0.2)
            raise TimeoutError("node did not register within 30s")
        return node

    def remove_node(self, node: _NodeProc) -> None:
        node.kill()
        self._nodes.remove(node)

    def kill_random_node(self) -> None:
        import random
        if self._nodes:
            self.remove_node(random.choice(self._nodes))

    def wait_for_nodes(self, timeout: float = 30) -> None:
        expect = 1 + len(self._nodes)
        deadline = time.time() + timeout
        while True:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= expect:
                return
            if time.time() >= deadline:
                raise TimeoutError(
                    f"expected {expect} alive nodes, have {len(alive)}")
            time.sleep(0.2)

    def shutdown(self) -> None:
        for node in list(self._nodes):
            try:
                self.remove_node(node)
            except Exception:
                pass
        if self._connected:
            ray_tpu.shutdown()
