"""User-defined metrics: Counter / Gauge / Histogram + Prometheus text.

Reference: ``python/ray/util/metrics.py`` (``Counter`` :137,
``Histogram`` :181, ``Gauge`` :256) flowing through the C++
OpenCensus pipeline to per-node Prometheus endpoints. Here a process-
local registry aggregates and ``export_prometheus()`` /
``serve_prometheus(port)`` expose the text format directly (one
process = one scrape target; tags become labels).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]


def _label_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name.isidentifier():
            raise ValueError(f"Invalid metric name {name!r}")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        out.update(tags or {})
        return out

    @property
    def info(self) -> Dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}

    def _samples(self) -> List[str]:
        raise NotImplementedError


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        key = _label_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def bound(self, tags: Optional[Dict[str, str]] = None
              ) -> "_BoundCounter":
        """Pre-resolve the label key once; the returned handle's inc()
        skips tag merging/sorting — for per-task hot paths."""
        return _BoundCounter(self, _label_key(self._merged(tags)))

    def _samples(self) -> List[str]:
        out = [f"# TYPE {self._name} counter"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self._name}{_fmt_labels(key)} {v}")
        return out


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(self._merged(tags))] = float(value)

    def _samples(self) -> List[str]:
        out = [f"# TYPE {self._name} gauge"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self._name}{_fmt_labels(key)} {v}")
        return out


class Histogram(Metric):
    def __init__(self, name, description="", boundaries=None,
                 tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._bounds = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(self._merged(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self._bounds) + 1))
            counts[bisect.bisect_left(self._bounds, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def _samples(self) -> List[str]:
        out = [f"# TYPE {self._name} histogram"]
        with self._lock:
            for key, counts in self._counts.items():
                cum = 0
                for bound, c in zip(self._bounds, counts):
                    cum += c
                    out.append(
                        f"{self._name}_bucket"
                        f"{_fmt_labels(key, le=bound)} {cum}")
                cum += counts[-1]
                out.append(
                    f'{self._name}_bucket{_fmt_labels(key, le="+Inf")} '
                    f"{cum}")
                out.append(f"{self._name}_count{_fmt_labels(key)} {cum}")
                out.append(
                    f"{self._name}_sum{_fmt_labels(key)} "
                    f"{self._sums[key]}")
        return out

    def bound(self, tags: Optional[Dict[str, str]] = None
              ) -> "_BoundHistogram":
        """Pre-resolved-label handle (see Counter.bound)."""
        return _BoundHistogram(self, _label_key(self._merged(tags)))


class _BoundCounter:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: Counter, key: Tuple):
        self._m = metric
        self._key = key

    def inc(self, value: float = 1.0) -> None:
        m = self._m
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0.0) + value


class _BoundHistogram:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: "Histogram", key: Tuple):
        self._m = metric
        self._key = key

    def observe(self, value: float) -> None:
        m = self._m
        with m._lock:
            counts = m._counts.setdefault(
                self._key, [0] * (len(m._bounds) + 1))
            counts[bisect.bisect_left(m._bounds, value)] += 1
            m._sums[self._key] = m._sums.get(self._key, 0.0) + value


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key: Tuple, le=None) -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if le is not None:
        parts.append(f'le="{le}"')
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


def export_prometheus() -> str:
    """All registered metrics in Prometheus text exposition format."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        lines.extend(m._samples())
    return "\n".join(lines) + "\n"


_metrics_server = None


def serve_prometheus(port: int = 0) -> int:
    """Start a /metrics HTTP endpoint; returns the bound port."""
    global _metrics_server
    import threading as _t
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = export_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    _metrics_server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    _t.Thread(target=_metrics_server.serve_forever, daemon=True).start()
    return _metrics_server.server_address[1]
