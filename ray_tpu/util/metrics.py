"""User-defined metrics: Counter / Gauge / Histogram + Prometheus text.

Reference: ``python/ray/util/metrics.py`` (``Counter`` :137,
``Histogram`` :181, ``Gauge`` :256) flowing through the C++
OpenCensus pipeline to per-node Prometheus endpoints. Here a process-
local registry aggregates and ``export_prometheus()`` /
``serve_prometheus(port)`` expose the text format directly.

Fleet export (the cluster metrics plane, ``core/metrics_plane.py``):
``export_snapshot()`` renders the same registry as structured data —
cumulative counter values, last-value gauges, histogram bucket vectors
— and :class:`MetricsReporter` ships those snapshots periodically as
``METRIC_REPORT`` messages so the controller can aggregate every
process's metrics into one scrape target (the reference's per-node
OpenCensus→Prometheus pipeline, collapsed onto our control plane).
"""

from __future__ import annotations

import bisect
import contextlib
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]


def _label_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name.isidentifier():
            raise ValueError(f"Invalid metric name {name!r}")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        out.update(tags or {})
        return out

    @property
    def info(self) -> Dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}

    def _samples(self) -> List[str]:
        raise NotImplementedError

    def snapshot(self) -> Dict:
        """Structured export for the fleet metrics plane: type, help
        text and every labelset's current value (cumulative for
        counters, last value for gauges, bucket vector + sum for
        histograms). Label keys ship as sorted ``[k, v]`` pairs so the
        payload survives JSON round-trips."""
        raise NotImplementedError

    def clear(self) -> None:
        """Drop every recorded labelset (test isolation)."""
        raise NotImplementedError

    def unregister(self) -> None:
        """Remove this metric from the process registry (it keeps
        working locally; it just stops being exported)."""
        with _registry_lock:
            try:
                _registry.remove(self)
            except ValueError:
                pass


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        key = _label_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def bound(self, tags: Optional[Dict[str, str]] = None
              ) -> "_BoundCounter":
        """Pre-resolve the label key once; the returned handle's inc()
        skips tag merging/sorting — for per-task hot paths."""
        return _BoundCounter(self, _label_key(self._merged(tags)))

    def _samples(self) -> List[str]:
        out = [f"# TYPE {self._name} counter"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self._name}{_fmt_labels(key)} {v}")
        return out

    def snapshot(self) -> Dict:
        with self._lock:
            samples = [[[list(kv) for kv in key], v]
                       for key, v in self._values.items()]
        return {"name": self._name, "type": "counter",
                "desc": self._description, "samples": samples}

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(self._merged(tags))] = float(value)

    def _samples(self) -> List[str]:
        out = [f"# TYPE {self._name} gauge"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self._name}{_fmt_labels(key)} {v}")
        return out

    def snapshot(self) -> Dict:
        with self._lock:
            samples = [[[list(kv) for kv in key], v]
                       for key, v in self._values.items()]
        return {"name": self._name, "type": "gauge",
                "desc": self._description, "samples": samples}

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class Histogram(Metric):
    def __init__(self, name, description="", boundaries=None,
                 tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._bounds = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(self._merged(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self._bounds) + 1))
            counts[bisect.bisect_left(self._bounds, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def _samples(self) -> List[str]:
        out = [f"# TYPE {self._name} histogram"]
        with self._lock:
            for key, counts in self._counts.items():
                cum = 0
                for bound, c in zip(self._bounds, counts):
                    cum += c
                    out.append(
                        f"{self._name}_bucket"
                        f"{_fmt_labels(key, le=bound)} {cum}")
                cum += counts[-1]
                out.append(
                    f'{self._name}_bucket{_fmt_labels(key, le="+Inf")} '
                    f"{cum}")
                out.append(f"{self._name}_count{_fmt_labels(key)} {cum}")
                out.append(
                    f"{self._name}_sum{_fmt_labels(key)} "
                    f"{self._sums[key]}")
        return out

    def bound(self, tags: Optional[Dict[str, str]] = None
              ) -> "_BoundHistogram":
        """Pre-resolved-label handle (see Counter.bound)."""
        return _BoundHistogram(self, _label_key(self._merged(tags)))

    def snapshot(self) -> Dict:
        with self._lock:
            samples = [[[list(kv) for kv in key], list(counts),
                        self._sums.get(key, 0.0)]
                       for key, counts in self._counts.items()]
        return {"name": self._name, "type": "histogram",
                "desc": self._description,
                "bounds": list(self._bounds), "samples": samples}

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()


class _BoundCounter:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: Counter, key: Tuple):
        self._m = metric
        self._key = key

    def inc(self, value: float = 1.0) -> None:
        m = self._m
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0.0) + value


class _BoundHistogram:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: "Histogram", key: Tuple):
        self._m = metric
        self._key = key

    def observe(self, value: float) -> None:
        m = self._m
        with m._lock:
            counts = m._counts.setdefault(
                self._key, [0] * (len(m._bounds) + 1))
            counts[bisect.bisect_left(m._bounds, value)] += 1
            m._sums[self._key] = m._sums.get(self._key, 0.0) + value


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(key: Tuple, le=None) -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in key]
    if le is not None:
        parts.append(f'le="{le}"')
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


def export_prometheus() -> str:
    """All registered metrics in Prometheus text exposition format."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry)
    for m in metrics:
        lines.extend(m._samples())
    return "\n".join(lines) + "\n"


def export_snapshot() -> List[Dict]:
    """Every registered metric's structured snapshot (the fleet-plane
    wire format: see :meth:`Metric.snapshot`)."""
    with _registry_lock:
        metrics = list(_registry)
    return [m.snapshot() for m in metrics]


# ---- registry scoping (test isolation) -------------------------------
def registry_snapshot() -> List["Metric"]:
    """The current registry membership (a mark for
    :func:`restore_registry`)."""
    with _registry_lock:
        return list(_registry)


def restore_registry(mark: List["Metric"]) -> int:
    """Unregister every metric created since ``mark`` (order and label
    state of surviving metrics untouched). Returns how many were
    dropped — the scoped reset a test suite needs so ``_registry``
    doesn't grow forever and one test's labelsets don't bleed into the
    next test's Prometheus snapshot."""
    keep = set(map(id, mark))
    with _registry_lock:
        before = len(_registry)
        _registry[:] = [m for m in _registry if id(m) in keep]
        return before - len(_registry)


@contextlib.contextmanager
def isolated_registry():
    """Context manager: metrics registered inside the block are
    unregistered on exit."""
    mark = registry_snapshot()
    try:
        yield
    finally:
        restore_registry(mark)


# ---- /metrics HTTP endpoint ------------------------------------------
_server_lock = threading.Lock()
_metrics_server = None
_metrics_thread = None


def serve_prometheus(port: int = 0, host: Optional[str] = None) -> int:
    """Start a /metrics HTTP endpoint; returns the bound port.

    Close-previous semantics: a second call stops the earlier server
    first (historically the module global was silently overwritten,
    leaking the old thread and socket). ``host`` defaults to
    ``RAY_TPU_METRICS_BIND_HOST`` (else loopback); bind ``0.0.0.0`` to
    let an external Prometheus scrape the process."""
    global _metrics_server, _metrics_thread
    import threading as _t
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = export_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    if host is None:
        host = os.environ.get("RAY_TPU_METRICS_BIND_HOST", "127.0.0.1")
    stop_prometheus()
    with _server_lock:
        _metrics_server = ThreadingHTTPServer((host, port), Handler)
        _metrics_thread = _t.Thread(
            target=_metrics_server.serve_forever,
            name="prometheus-metrics", daemon=True)
        _metrics_thread.start()
        return _metrics_server.server_address[1]


def stop_prometheus(timeout: float = 5.0) -> bool:
    """Stop the endpoint started by :func:`serve_prometheus` (close the
    socket, join the thread). Returns True if a server was running."""
    global _metrics_server, _metrics_thread
    with _server_lock:
        server, thread = _metrics_server, _metrics_thread
        _metrics_server = _metrics_thread = None
    if server is None:
        return False
    try:
        server.shutdown()
        server.server_close()
    except Exception:
        pass
    if thread is not None:
        thread.join(timeout)
    return True


# ---- periodic fleet reporter -----------------------------------------
#: one ACTIVE reporter per process: the registry is process-global, so
#: colocated runtimes (head mode hosts controller + node manager +
#: driver in one process) must not each ship the same snapshot — the
#: fleet merge would multiply every sample by the number of roles.
#: The highest-precedence role claims the process; ties go to the
#: newest claimant (a restarted session supersedes a stale reporter).
_ROLE_RANK = {"controller": 0, "node": 1, "worker": 2, "driver": 3}
_active_reporter_lock = threading.Lock()
_active_reporter: Optional["MetricsReporter"] = None


def _claim_reporter(rep: "MetricsReporter") -> bool:
    global _active_reporter
    with _active_reporter_lock:
        cur = _active_reporter
        rank = _ROLE_RANK.get(rep.origin.get("role"), 2)
        if cur is None or not cur.active or \
                rank >= _ROLE_RANK.get(cur.origin.get("role"), 2):
            if cur is not None:
                cur.active = False
            _active_reporter = rep
            return True
        return False


class MetricsReporter:
    """Ships this process's metric snapshots to the controller.

    Fire-and-forget like the flight recorder's flush: ``send`` enqueues
    a ``METRIC_REPORT`` payload into the process's async flusher (the
    reliable layer gives it exactly-once-effect at the controller; a
    chaos drop costs a retransmit, never a stall). Reports supersede
    each other — a snapshot is cumulative — so before shipping a new
    one the reporter asks ``pending_drop`` to abandon in-flight older
    reports beyond a small bound (drop-OLDEST, counted in
    ``runtime_metric_reports_dropped_total``): a dead link can never
    grow the retransmit ring or block a task."""

    #: in-flight (unacked) reports kept alive; older ones are dropped
    MAX_PENDING = 4

    def __init__(self, send: Callable[[dict], None], origin: Dict,
                 interval_s: float = 1.0, enabled: bool = True,
                 pending_drop: Optional[Callable[[int], int]] = None):
        self._send = send
        self.origin = dict(origin)
        self._interval = interval_s
        self.enabled = enabled
        self._pending_drop = pending_drop
        self._seq = 0
        self._lock = threading.Lock()
        self._last = 0.0
        self.dropped = 0
        self._dropped_metric = None
        #: False when another (higher-precedence or newer) reporter in
        #: this process owns the registry — see _claim_reporter
        self.active = _claim_reporter(self)

    def _count_drop(self, n: int, reason: str) -> None:
        self.dropped += n
        m = self._dropped_metric
        if m is None:
            try:
                from ray_tpu.core.metric_defs import runtime_metrics
                m = self._dropped_metric = \
                    runtime_metrics().metric_reports_dropped
            except Exception:
                return
        try:
            m.inc(n, tags={"reason": reason})
        except Exception:
            pass

    def report_now(self) -> Optional[dict]:
        """Build and ship one snapshot report; returns the payload (or
        None when disabled / passive / the send path is down). Never
        raises."""
        if not self.enabled or not self.active:
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._last = time.monotonic()
        if self._pending_drop is not None:
            try:
                stale = self._pending_drop(self.MAX_PENDING - 1)
                if stale:
                    self._count_drop(stale, "superseded")
            except Exception:
                pass
        payload = {"origin": self.origin, "seq": seq,
                   "ts": time.time(), "metrics": export_snapshot()}
        try:
            self._send(payload)
        except Exception:
            # boot/shutdown window — metrics are observability; losing
            # a report must not hurt the process
            self._count_drop(1, "send_failed")
            return None
        return payload

    def release(self) -> None:
        """Process-shutdown hook: give up the process claim so a later
        runtime in this process (or a colocated lower-precedence one)
        can report again."""
        global _active_reporter
        self.active = False
        with _active_reporter_lock:
            if _active_reporter is self:
                _active_reporter = None

    def maybe_report(self, now: Optional[float] = None) -> None:
        """Interval-gated report (call from any periodic loop; cheap
        no-op inside the interval)."""
        if not self.enabled or not self.active:
            return
        if (now or time.monotonic()) - self._last >= self._interval:
            self.report_now()


def make_reporter(send, origin: Dict, config,
                  pending_drop=None) -> MetricsReporter:
    """Build a process's reporter from config knobs."""
    return MetricsReporter(
        send, origin,
        interval_s=getattr(config, "metrics_report_interval_ms",
                           1000) / 1000.0,
        enabled=getattr(config, "enable_metrics_report", True),
        pending_drop=pending_drop)
