"""Dask-on-ray_tpu scheduler shim (reference: ``python/ray/util/dask/``
— ``ray_dask_get``, a dask scheduler that runs each task in the dask
graph as a Ray task, with ObjectRefs flowing between them).

Usage::

    import dask
    from ray_tpu.util.dask import ray_dask_get
    dask.config.set(scheduler=ray_dask_get)   # or compute(scheduler=...)

Gated: raises a clear error if dask is not installed (the TPU image
does not bake it)."""

from __future__ import annotations

from typing import Any, Dict, List

import ray_tpu


def _require_dask():
    try:
        import dask  # noqa: F401
        from dask.core import get_dependencies, istask  # noqa: F401
    except ImportError as e:  # pragma: no cover - dask not in image
        raise ImportError(
            "ray_tpu.util.dask needs the `dask` package (not baked into "
            "the hermetic TPU image — add it to the image to use the "
            "shim)") from e


@ray_tpu.remote
def _dask_task(func_and_args):
    func, args = func_and_args
    return func(*args)


def ray_dask_get(dsk: Dict, keys, **_kwargs) -> Any:
    """A dask ``get``: topologically walk the graph, submitting each
    task as a remote task; dependencies pass as ObjectRefs resolved by
    the runtime (zero-copy through the object store)."""
    _require_dask()
    from dask.core import get_dependencies, istask, toposort

    refs: Dict[Any, Any] = {}

    def resolve(v):
        if isinstance(v, list):
            return [resolve(x) for x in v]
        if isinstance(v, tuple) and istask(v):
            func, args = v[0], [resolve(a) for a in v[1:]]
            return func(*[ray_tpu.get(a) if _is_ref(a) else a
                          for a in args])
        if v in refs:
            return refs[v]
        return v

    for key in toposort(dsk):
        val = dsk[key]
        if istask(val):
            func, arg_exprs = val[0], list(val[1:])

            # materialize args: substitute dependency refs
            def subst(expr):
                if isinstance(expr, list):
                    return [subst(x) for x in expr]
                if isinstance(expr, tuple) and istask(expr):
                    f, rest = expr[0], [subst(x) for x in expr[1:]]
                    return (f,) + tuple(rest)
                if expr in refs:
                    return refs[expr]
                return expr

            args = [subst(a) for a in arg_exprs]
            refs[key] = _dask_task.remote((_Evaluator(func), args))
        else:
            refs[key] = resolve(val)

    def fetch(k):
        v = refs[k]
        return ray_tpu.get(v) if _is_ref(v) else v

    if isinstance(keys, list):
        return [fetch(k) if not isinstance(k, list)
                else [fetch(kk) for kk in k] for k in keys]
    return fetch(keys)


class _Evaluator:
    """Evaluates nested dask task expressions inside the worker (inner
    tuples arrive unexecuted; ObjectRef args are already resolved)."""

    def __init__(self, func):
        self.func = func

    def __call__(self, *args):
        from dask.core import istask

        def ev(x):
            if isinstance(x, list):
                return [ev(i) for i in x]
            if isinstance(x, tuple) and istask(x):
                return x[0](*[ev(a) for a in x[1:]])
            return x

        return self.func(*[ev(a) for a in args])


def _is_ref(v) -> bool:
    from ray_tpu.core.object_ref import ObjectRef
    return isinstance(v, ObjectRef)


def enable_dask_on_ray() -> None:
    """Set ray_dask_get as dask's default scheduler."""
    _require_dask()
    import dask
    dask.config.set(scheduler=ray_dask_get)


def disable_dask_on_ray() -> None:
    _require_dask()
    import dask
    dask.config.set(scheduler=None)
