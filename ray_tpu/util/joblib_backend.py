"""joblib backend: run scikit-learn/joblib Parallel work as ray tasks.

Reference: ``python/ray/util/joblib/`` — ``register_ray()`` installs a
joblib ParallelBackend whose ``apply_async`` submits batches to the
cluster, so ``with joblib.parallel_backend("ray_tpu"): Parallel(...)``
fans out across workers with no scikit-learn changes.
"""

from __future__ import annotations

from typing import Any

__all__ = ["register_ray"]


def register_ray() -> None:
    """Register the ``"ray_tpu"`` joblib backend (reference:
    ``ray.util.joblib.register_ray``)."""
    from joblib.parallel import ParallelBackendBase, register_parallel_backend

    import ray_tpu

    @ray_tpu.remote
    def _run_batch(batch):
        return batch()

    class _RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        #: joblib uses this to size batches; cluster CPU count is the
        #: honest parallelism bound
        def effective_n_jobs(self, n_jobs: int) -> int:
            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs == -1 or n_jobs is None:
                return max(1, cpus)
            return max(1, min(n_jobs, cpus))

        def configure(self, n_jobs: int = 1, parallel=None,
                      **backend_args: Any) -> int:
            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def apply_async(self, func, callback=None):
            ref = _run_batch.remote(func)
            return _RayFuture(ref, callback)

        def abort_everything(self, ensure_ready: bool = True) -> None:
            pass  # refs are dropped; tasks finish or are GC'd

    class _RayFuture:
        def __init__(self, ref, callback):
            self._ref = ref
            self._callback = callback
            self._done = False
            self._value = None

        def get(self, timeout: float = None):
            if not self._done:
                self._value = ray_tpu.get(self._ref, timeout=timeout)
                self._done = True
                if self._callback is not None:
                    self._callback(self._value)
            return self._value

    register_parallel_backend("ray_tpu", _RayTpuBackend)
