"""Utility libraries (reference: ``python/ray/util/``)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Queue
from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
    placement_group_table,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "Queue",
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "placement_group_table",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
