"""Parallel iterators (reference: ``python/ray/util/iter.py`` —
``from_items``/``from_iterators``/``from_range`` producing a
``ParallelIterator`` of sharded streams backed by actors, with
``for_each``/``filter``/``batch``/``gather_sync``/``gather_async``/
``union``/``repartition``)."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, TypeVar

import ray_tpu

T = TypeVar("T")
U = TypeVar("U")


def from_items(items: List[T], num_shards: int = 2,
               repeat: bool = False) -> "ParallelIterator[T]":
    shards = [items[i::num_shards] for i in range(num_shards)]
    return from_iterators(
        [(lambda s=s: iter(s)) for s in shards], repeat=repeat,
        name=f"from_items[{len(items)}]")


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> "ParallelIterator[int]":
    bounds = [(i * n // num_shards, (i + 1) * n // num_shards)
              for i in range(num_shards)]
    return from_iterators(
        [(lambda lo=lo, hi=hi: iter(range(lo, hi)))
         for lo, hi in bounds],
        repeat=repeat, name=f"from_range[{n}]")


def from_iterators(creators: List[Callable[[], Iterable[T]]],
                   repeat: bool = False,
                   name: str = "from_iterators"
                   ) -> "ParallelIterator[T]":
    return ParallelIterator(
        [_IterShard.remote(c, repeat) for c in creators], name)


@ray_tpu.remote(num_cpus=0.25)
class _IterShard:
    """Actor hosting one shard's iterator + its transform chain."""

    def __init__(self, creator: Callable[[], Iterable], repeat: bool):
        self._creator = creator
        self._repeat = repeat
        self._ops: List[Any] = []
        self._it: Iterator = None  # type: ignore[assignment]
        self._reset()

    def _reset(self) -> None:
        base = iter(self._creator())
        if self._repeat:
            base = itertools.chain.from_iterable(
                iter(self._creator()) for _ in itertools.count())
        it = base
        for kind, fn in self._ops:
            it = _apply_op(it, kind, fn)
        self._it = it

    def push_op(self, kind: str, fn: Any) -> None:
        self._ops.append((kind, fn))
        self._reset()

    def next_batch(self, n: int) -> List[Any]:
        out = list(itertools.islice(self._it, n))
        return out


def _apply_op(it: Iterator, kind: str, fn: Any) -> Iterator:
    if kind == "for_each":
        return map(fn, it)
    if kind == "filter":
        return filter(fn, it)
    if kind == "batch":
        def batched(src=it, size=fn):
            while True:
                chunk = list(itertools.islice(src, size))
                if not chunk:
                    return
                yield chunk
        return batched()
    if kind == "flatten":
        return itertools.chain.from_iterable(it)
    raise ValueError(kind)


class ParallelIterator:
    """Handle over sharded remote iterators."""

    def __init__(self, shards: List[Any], name: str):
        self._shards = shards
        self.name = name

    def __repr__(self):
        return f"ParallelIterator[{self.name}, {len(self._shards)} shards]"

    def num_shards(self) -> int:
        return len(self._shards)

    # -- transforms (lazy, applied shard-side) -------------------------
    def for_each(self, fn: Callable[[T], U]) -> "ParallelIterator[U]":
        ray_tpu.get([s.push_op.remote("for_each", fn)
                     for s in self._shards])
        return self

    def filter(self, fn: Callable[[T], bool]) -> "ParallelIterator[T]":
        ray_tpu.get([s.push_op.remote("filter", fn)
                     for s in self._shards])
        return self

    def batch(self, n: int) -> "ParallelIterator[List[T]]":
        ray_tpu.get([s.push_op.remote("batch", n)
                     for s in self._shards])
        return self

    def flatten(self) -> "ParallelIterator[Any]":
        ray_tpu.get([s.push_op.remote("flatten", None)
                     for s in self._shards])
        return self

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(self._shards + other._shards,
                                f"union({self.name},{other.name})")

    # -- consumption ---------------------------------------------------
    def gather_sync(self, batch: int = 64) -> Iterator[T]:
        """Round-robin over shards, in shard order (deterministic)."""
        live = list(self._shards)
        while live:
            futs = [s.next_batch.remote(batch) for s in live]
            results = ray_tpu.get(futs)
            nxt = []
            for s, chunk in zip(live, results):
                yield from chunk
                if len(chunk) == batch:
                    nxt.append(s)
            live = nxt

    def gather_async(self, batch: int = 64) -> Iterator[T]:
        """Yield from whichever shard is ready first."""
        pending = {s.next_batch.remote(batch): s for s in self._shards}
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1)
            fut = ready[0]
            shard = pending.pop(fut)
            chunk = ray_tpu.get(fut)
            yield from chunk
            if len(chunk) == batch:
                pending[shard.next_batch.remote(batch)] = shard

    def take(self, n: int) -> List[T]:
        out = []
        for item in self.gather_sync():
            out.append(item)
            if len(out) >= n:
                break
        return out

    def stop(self) -> None:
        for s in self._shards:
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
