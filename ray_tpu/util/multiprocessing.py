"""multiprocessing.Pool shim over tasks.

Reference: ``python/ray/util/multiprocessing/pool.py`` — the drop-in
``Pool`` API (map/starmap/apply/imap/async variants) executing on the
cluster instead of local processes.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs if isinstance(self._refs, list)
                     else [self._refs],
                     num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class _PoolWorker:
    """One pool slot: an actor, so ``processes`` truly bounds
    concurrency (the reference Pool is also actor-backed)."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run(self, fn, args, kwargs):
        return fn(*args, **kwargs)


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_remote_args: Optional[dict] = None):
        self._n = processes or 8
        self._remote_args = ray_remote_args or {"num_cpus": 1}
        self._closed = False
        actor_cls = ray_tpu.remote(**self._remote_args)(_PoolWorker)
        self._workers = [actor_cls.remote(initializer, initargs)
                         for _ in range(self._n)]
        self._rr = 0

    def _submit(self, fn, args, kwargs):
        worker = self._workers[self._rr % self._n]
        self._rr += 1
        return worker.run.remote(fn, args, kwargs)

    def _check(self):
        if self._closed:
            raise ValueError("Pool is closed")

    # -- apply --------------------------------------------------------
    def apply(self, fn, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check()
        return AsyncResult(
            [self._submit(fn, args, kwds or {})], single=True)

    # -- map ----------------------------------------------------------
    def map(self, fn, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check()
        refs = [self._submit(fn, (x,), {}) for x in iterable]
        return AsyncResult(refs)

    def starmap(self, fn, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(fn, iterable).get()

    def starmap_async(self, fn, iterable: Iterable[tuple]) -> AsyncResult:
        self._check()
        refs = [self._submit(fn, tuple(x), {}) for x in iterable]
        return AsyncResult(refs)

    def imap(self, fn, iterable: Iterable, chunksize: int = 1):
        self._check()
        refs = [self._submit(fn, (x,), {}) for x in iterable]
        for ref in refs:
            yield ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable: Iterable, chunksize: int = 1):
        self._check()
        pending = [self._submit(fn, (x,), {}) for x in iterable]
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(ready[0])

    # -- lifecycle ----------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []

    def join(self) -> None:
        if not self._closed:
            raise ValueError("join() before close()")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
