"""Ray Client equivalent: drive a remote cluster from a process that is
not part of it (reference: ``python/ray/util/client/worker.py:81`` —
``ray.init("ray://host:port")`` proxies the public API over gRPC to a
server hosting a real driver). Here the wire is ZMQ over TCP with
pickled frames; the server process is a normal cluster driver that
executes API calls on each client's behalf and leases object/actor
references to the connection.
"""

from ray_tpu.util.client.server import ClientServer
from ray_tpu.util.client.worker import ClientWorker, connect

__all__ = ["ClientServer", "ClientWorker", "connect"]
