"""Client server: hosts a proxy driver for remote clients.

Reference: ``python/ray/util/client/server/server.py`` (RayletServicer —
per-client object/actor leases, function cache, disconnect GC). The
server runs inside a process that is already a cluster driver (head
node, or ``ray-tpu client-server``); each connected client gets its own
reference table so a disconnect releases exactly its leases.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict

import zmq

from ray_tpu.util.client import common as C

logger = logging.getLogger(__name__)

#: Hard cap on how long a single get/wait handler may block, regardless
#: of the client-requested timeout. Clients re-poll (worker.py loops in
#: the same slice), so one never-ready object can't pin a handler slot
#: for unbounded time. Defined in common.py: both sides must agree.
_BLOCK_SLICE_S = C.BLOCK_SLICE_S


class _ClientSession:
    def __init__(self):
        self.refs: Dict[bytes, Any] = {}      # ref_id -> ObjectRef
        self.actors: Dict[bytes, Any] = {}    # actor_ref_id -> handle
        self.functions: Dict[bytes, Any] = {} # fn_id -> RemoteFunction
        self.classes: Dict[bytes, Any] = {}   # cls_id -> ActorClass
        self.last_seen = time.monotonic()
        #: ops currently executing on the handler pool — the idle reaper
        #: must never drop a session mid-operation (handlers run
        #: concurrently with the loop thread since the pool landed)
        self.inflight = 0


class ClientServer:
    """Serves the client protocol on a TCP ROUTER socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = C.DEFAULT_PORT,
                 idle_disconnect_s: float = 120.0, num_handlers: int = 8):
        # Default bind is loopback: the protocol deserializes pickled
        # payloads (arbitrary code execution by design, same trust model
        # as the reference's ray://). Exposing it beyond the machine is
        # an explicit operator opt-in (host="0.0.0.0") for trusted
        # networks only.
        import ray_tpu
        if not ray_tpu.is_initialized():
            raise RuntimeError(
                "ClientServer must run inside an initialized driver "
                "(call ray_tpu.init() first)")
        self._ray = ray_tpu
        self.host = host
        self.port = port
        self.idle_disconnect_s = idle_disconnect_s
        self._sessions: Dict[bytes, _ClientSession] = {}
        self._sessions_lock = threading.Lock()
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        self._sock.bind(f"tcp://{host}:{port}")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="client-server", daemon=True)
        # Ops run on a pool so one slow client (big arg deserialization,
        # a get that has to pull a large object) can't stall every other
        # connection. Replies funnel back to the loop thread via a queue
        # + inproc wake socket: the ROUTER socket stays single-threaded.
        self._pool = ThreadPoolExecutor(
            max_workers=num_handlers, thread_name_prefix="client-op")
        self._reply_q: "queue.Queue[tuple]" = queue.Queue()
        self._wake_addr = f"inproc://client-server-wake-{id(self):x}"
        self._wake_pull = self._ctx.socket(zmq.PULL)
        self._wake_pull.bind(self._wake_addr)
        self._tls = threading.local()
        self._ref_seq = 0
        self._ref_seq_lock = threading.Lock()

    def start(self) -> "ClientServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=3)
        self._pool.shutdown(wait=False)
        for s in (self._sock, self._wake_pull):
            try:
                s.close(0)
            except Exception:
                pass

    # ------------------------------------------------------------- loop
    def _loop(self) -> None:
        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        poller.register(self._wake_pull, zmq.POLLIN)
        last_reap = time.monotonic()
        while not self._stop.is_set():
            events = dict(poller.poll(timeout=250))
            if self._wake_pull in events:
                while self._wake_pull.poll(0):
                    self._wake_pull.recv()
            self._drain_replies()
            if self._sock not in events:
                # reap only on idle polls, and only after every received
                # message has bumped its session's last_seen below — a
                # request sitting in the recv or pool queue must never
                # lose its session to the reaper
                if time.monotonic() - last_reap > 10.0:
                    self._reap_idle()
                    last_reap = time.monotonic()
                continue
            while self._sock.poll(0):
                frames = self._sock.recv_multipart()
                identity, payload = frames[0], frames[-1]
                try:
                    req = C.loads(payload)
                except Exception as e:  # noqa: BLE001
                    self._reply(identity, {"ok": False,
                                           "error": C.dumps(e)})
                    continue
                # touch the session on the loop thread BEFORE handing to
                # the pool: protects it from the reaper while queued
                self._session(identity)
                self._pool.submit(self._handle, identity, req)

    def _handle(self, identity: bytes, req: dict) -> None:
        session = self._session(identity)
        with self._sessions_lock:
            session.inflight += 1
        try:
            out = self._dispatch(identity, req, session)
        except BaseException as e:  # noqa: BLE001
            logger.debug("client op %s failed", req.get("op"),
                         exc_info=True)
            out = {"ok": False, "error": C.dumps(e)}
        finally:
            with self._sessions_lock:
                session.inflight -= 1
                session.last_seen = time.monotonic()
        out["rid"] = req.get("rid")
        self._reply(identity, out)

    def _drain_replies(self) -> None:
        while True:
            try:
                identity, blob = self._reply_q.get_nowait()
            except queue.Empty:
                return
            try:
                self._sock.send_multipart([identity, blob])
            except Exception:
                pass

    def _reply(self, identity: bytes, out: dict) -> None:
        self._reply_q.put((identity, C.dumps(out)))
        if threading.current_thread() is self._thread:
            self._drain_replies()
        else:
            self._wake()

    def _wake(self) -> None:
        push = getattr(self._tls, "push", None)
        if push is None:
            push = self._ctx.socket(zmq.PUSH)
            push.connect(self._wake_addr)
            self._tls.push = push
        try:
            push.send(b"", zmq.DONTWAIT)
        except Exception:
            pass

    def _session(self, identity: bytes) -> _ClientSession:
        with self._sessions_lock:
            s = self._sessions.get(identity)
            if s is None:
                s = self._sessions[identity] = _ClientSession()
            s.last_seen = time.monotonic()
            return s

    def _reap_idle(self) -> None:
        now = time.monotonic()
        with self._sessions_lock:
            idle = [i for i, s in self._sessions.items()
                    if s.inflight == 0
                    and now - s.last_seen > self.idle_disconnect_s]
        for identity in idle:
            logger.info("client %s idle; releasing refs",
                        identity.hex()[:8])
            self._drop_session(identity)

    def _drop_session(self, identity: bytes) -> None:
        with self._sessions_lock:
            s = self._sessions.pop(identity, None)
        if s is None:
            return
        s.refs.clear()
        for h in s.actors.values():
            # only detached/named actors survive their creating client
            try:
                if not getattr(h, "_detached", False):
                    self._ray.kill(h)
            except Exception:
                pass
        s.actors.clear()

    def _mint(self) -> bytes:
        with self._ref_seq_lock:
            self._ref_seq += 1
            seq = self._ref_seq
        return os.urandom(12) + seq.to_bytes(4, "little")

    # -------------------------------------------------------- marshaling
    def _resolve_markers(self, session: _ClientSession, obj):
        """Replace _RefMarker instances (from pickled ClientObjectRefs)
        with the server-held ObjectRefs, recursively through the common
        containers (same depth the reference's marker pass covers)."""
        if isinstance(obj, C._RefMarker):
            ref = session.refs.get(obj.ref_id)
            if ref is None:
                raise KeyError(
                    f"client ref {obj.ref_id.hex()[:12]} is not leased "
                    f"to this connection")
            return ref
        if isinstance(obj, (list, tuple)):
            vals = [self._resolve_markers(session, v) for v in obj]
            return type(obj)(vals) if not isinstance(obj, tuple) \
                else tuple(vals)
        if isinstance(obj, dict):
            return {k: self._resolve_markers(session, v)
                    for k, v in obj.items()}
        return obj

    def _lease_ref(self, session: _ClientSession, ref) -> bytes:
        rid = self._mint()
        session.refs[rid] = ref
        return rid

    # --------------------------------------------------------- dispatch
    def _dispatch(self, identity: bytes, req: dict,
                  session: _ClientSession) -> dict:
        op = req["op"]
        for rid in req.get("release") or ():
            session.refs.pop(rid, None)
        for aid in req.get("release_actors") or ():
            session.actors.pop(aid, None)
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ValueError(f"unknown client op {op!r}")
        return handler(session, req)

    def _op_connect(self, session, req) -> dict:
        with self._sessions_lock:
            n = len(self._sessions)
        info = {
            "ok": True,
            "num_clients": n,
            "resources": self._ray.cluster_resources(),
        }
        return info

    def _op_disconnect(self, session, req) -> dict:
        # release happens via identity lookup in _drop_session
        with self._sessions_lock:
            idents = [i for i, s in self._sessions.items() if s is session]
        for identity in idents:
            self._drop_session(identity)
        return {"ok": True}

    def _op_put(self, session, req) -> dict:
        value = self._resolve_markers(session, C.loads(req["value"]))
        ref = self._ray.put(value)
        return {"ok": True, "ref_id": self._lease_ref(session, ref)}

    @staticmethod
    def _clamp(timeout) -> float:
        # never let a client-supplied timeout (or None) hold a handler
        # slot longer than one slice; the client loops (worker.py get/wait)
        return _BLOCK_SLICE_S if timeout is None \
            else max(0.0, min(float(timeout), _BLOCK_SLICE_S))

    def _op_get(self, session, req) -> dict:
        refs = [session.refs[rid] for rid in req["ref_ids"]]
        uniq = list(dict.fromkeys(refs))
        ready, _ = self._ray.wait(
            uniq, num_returns=len(uniq),
            timeout=self._clamp(req.get("timeout")))
        if len(ready) < len(uniq):
            return {"ok": True, "pending": True}
        vals = self._ray.get(refs)
        return {"ok": True, "values": C.dumps(vals)}

    def _op_wait(self, session, req) -> dict:
        by_id = {session.refs[rid]: rid for rid in req["ref_ids"]}
        ready, pending = self._ray.wait(
            list(by_id.keys()), num_returns=req.get("num_returns", 1),
            timeout=self._clamp(req.get("timeout")))
        return {"ok": True,
                "ready": [by_id[r] for r in ready],
                "pending": [by_id[r] for r in pending]}

    def _op_release(self, session, req) -> dict:
        for rid in req["ref_ids"]:
            session.refs.pop(rid, None)
        return {"ok": True}

    def _op_release_actor(self, session, req) -> dict:
        session.actors.pop(req["actor_id"], None)
        return {"ok": True}

    def _op_register_fn(self, session, req) -> dict:
        fn = C.loads(req["func"])
        opts = req.get("options") or {}
        fn_id = self._mint()
        session.functions[fn_id] = self._ray.remote(**opts)(fn) \
            if opts else self._ray.remote(fn)
        return {"ok": True, "fn_id": fn_id}

    def _op_call_fn(self, session, req) -> dict:
        rf = session.functions[req["fn_id"]]
        if req.get("options"):
            rf = rf.options(**req["options"])
        args, kwargs = self._resolve_markers(
            session, C.loads(req["args"]))
        refs = rf.remote(*args, **kwargs)
        many = isinstance(refs, list)
        out = [self._lease_ref(session, r)
               for r in (refs if many else [refs])]
        return {"ok": True, "ref_ids": out, "many": many}

    def _op_register_class(self, session, req) -> dict:
        cls = C.loads(req["cls"])
        opts = req.get("options") or {}
        cls_id = self._mint()
        session.classes[cls_id] = self._ray.remote(**opts)(cls) \
            if opts else self._ray.remote(cls)
        methods = [n for n in dir(cls)
                   if not n.startswith("_") and callable(getattr(cls, n))]
        return {"ok": True, "cls_id": cls_id, "methods": methods}

    def _op_create_actor(self, session, req) -> dict:
        ac = session.classes[req["cls_id"]]
        opts = req.get("options") or {}
        if opts:
            ac = ac.options(**opts)
        args, kwargs = self._resolve_markers(
            session, C.loads(req["args"]))
        handle = ac.remote(*args, **kwargs)
        if opts.get("lifetime") == "detached" or opts.get("name"):
            handle._detached = True
        aid = self._mint()
        session.actors[aid] = handle
        return {"ok": True, "actor_id": aid}

    def _op_call_method(self, session, req) -> dict:
        handle = session.actors[req["actor_id"]]
        method = getattr(handle, req["method"])
        if req.get("options"):
            method = method.options(**req["options"])
        args, kwargs = self._resolve_markers(
            session, C.loads(req["args"]))
        refs = method.remote(*args, **kwargs)
        many = isinstance(refs, list)
        out = [self._lease_ref(session, r)
               for r in (refs if many else [refs])]
        return {"ok": True, "ref_ids": out, "many": many}

    def _op_get_actor(self, session, req) -> dict:
        handle = self._ray.get_actor(
            req["name"], namespace=req.get("namespace", ""))
        handle._detached = True   # named: outlives this client
        methods = [n for n in dir(handle)
                   if not n.startswith("_")]
        aid = self._mint()
        session.actors[aid] = handle
        # handle exposes methods dynamically; ask the actor class
        return {"ok": True, "actor_id": aid,
                "methods": getattr(handle, "_method_names", methods)}

    def _op_kill_actor(self, session, req) -> dict:
        handle = session.actors.get(req["actor_id"])
        if handle is not None:
            self._ray.kill(handle, no_restart=req.get("no_restart", True))
        return {"ok": True}

    def _op_cancel(self, session, req) -> dict:
        ref = session.refs.get(req["ref_id"])
        if ref is not None:
            self._ray.cancel(ref, force=req.get("force", False))
        return {"ok": True}

    def _op_cluster_info(self, session, req) -> dict:
        kind = req.get("kind", "resources")
        if kind == "resources":
            data = self._ray.cluster_resources()
        elif kind == "available":
            data = self._ray.available_resources()
        elif kind == "nodes":
            data = self._ray.nodes()
        else:
            raise ValueError(f"unknown cluster_info kind {kind!r}")
        return {"ok": True, "data": C.dumps(data)}
