"""Client server: hosts a proxy driver for remote clients.

Reference: ``python/ray/util/client/server/server.py`` (RayletServicer —
per-client object/actor leases, function cache, disconnect GC). The
server runs inside a process that is already a cluster driver (head
node, or ``ray-tpu client-server``); each connected client gets its own
reference table so a disconnect releases exactly its leases.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict

import zmq

from ray_tpu.util.client import common as C

logger = logging.getLogger(__name__)


class _ClientSession:
    def __init__(self):
        self.refs: Dict[bytes, Any] = {}      # ref_id -> ObjectRef
        self.actors: Dict[bytes, Any] = {}    # actor_ref_id -> handle
        self.functions: Dict[bytes, Any] = {} # fn_id -> RemoteFunction
        self.classes: Dict[bytes, Any] = {}   # cls_id -> ActorClass
        self.last_seen = time.monotonic()


class ClientServer:
    """Serves the client protocol on a TCP ROUTER socket."""

    def __init__(self, host: str = "0.0.0.0", port: int = C.DEFAULT_PORT,
                 idle_disconnect_s: float = 120.0):
        import ray_tpu
        if not ray_tpu.is_initialized():
            raise RuntimeError(
                "ClientServer must run inside an initialized driver "
                "(call ray_tpu.init() first)")
        self._ray = ray_tpu
        self.host = host
        self.port = port
        self.idle_disconnect_s = idle_disconnect_s
        self._sessions: Dict[bytes, _ClientSession] = {}
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        self._sock.bind(f"tcp://{host}:{port}")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="client-server", daemon=True)
        self._ref_seq = 0

    def start(self) -> "ClientServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=3)
        try:
            self._sock.close(0)
        except Exception:
            pass

    # ------------------------------------------------------------- loop
    def _loop(self) -> None:
        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        last_reap = time.monotonic()
        while not self._stop.is_set():
            if not dict(poller.poll(timeout=250)):
                if time.monotonic() - last_reap > 10.0:
                    self._reap_idle()
                    last_reap = time.monotonic()
                continue
            frames = self._sock.recv_multipart()
            identity, payload = frames[0], frames[-1]
            try:
                req = C.loads(payload)
            except Exception as e:  # noqa: BLE001
                self._reply(identity, {"ok": False, "error": C.dumps(e)})
                continue
            try:
                out = self._dispatch(identity, req)
            except BaseException as e:  # noqa: BLE001
                logger.debug("client op %s failed", req.get("op"),
                             exc_info=True)
                out = {"ok": False, "error": C.dumps(e)}
            out["rid"] = req.get("rid")
            self._reply(identity, out)

    def _reply(self, identity: bytes, out: dict) -> None:
        try:
            self._sock.send_multipart([identity, C.dumps(out)])
        except Exception:
            pass

    def _session(self, identity: bytes) -> _ClientSession:
        s = self._sessions.get(identity)
        if s is None:
            s = self._sessions[identity] = _ClientSession()
        s.last_seen = time.monotonic()
        return s

    def _reap_idle(self) -> None:
        now = time.monotonic()
        for identity in list(self._sessions):
            s = self._sessions[identity]
            if now - s.last_seen > self.idle_disconnect_s:
                logger.info("client %s idle; releasing %d refs",
                            identity.hex()[:8], len(s.refs))
                self._drop_session(identity)

    def _drop_session(self, identity: bytes) -> None:
        s = self._sessions.pop(identity, None)
        if s is None:
            return
        s.refs.clear()
        for h in s.actors.values():
            # only detached/named actors survive their creating client
            try:
                if not getattr(h, "_detached", False):
                    self._ray.kill(h)
            except Exception:
                pass
        s.actors.clear()

    def _mint(self) -> bytes:
        self._ref_seq += 1
        return os.urandom(12) + self._ref_seq.to_bytes(4, "little")

    # -------------------------------------------------------- marshaling
    def _resolve_markers(self, session: _ClientSession, obj):
        """Replace _RefMarker instances (from pickled ClientObjectRefs)
        with the server-held ObjectRefs, recursively through the common
        containers (same depth the reference's marker pass covers)."""
        if isinstance(obj, C._RefMarker):
            ref = session.refs.get(obj.ref_id)
            if ref is None:
                raise KeyError(
                    f"client ref {obj.ref_id.hex()[:12]} is not leased "
                    f"to this connection")
            return ref
        if isinstance(obj, (list, tuple)):
            vals = [self._resolve_markers(session, v) for v in obj]
            return type(obj)(vals) if not isinstance(obj, tuple) \
                else tuple(vals)
        if isinstance(obj, dict):
            return {k: self._resolve_markers(session, v)
                    for k, v in obj.items()}
        return obj

    def _lease_ref(self, session: _ClientSession, ref) -> bytes:
        rid = self._mint()
        session.refs[rid] = ref
        return rid

    # --------------------------------------------------------- dispatch
    def _dispatch(self, identity: bytes, req: dict) -> dict:
        op = req["op"]
        session = self._session(identity)
        for rid in req.get("release") or ():
            session.refs.pop(rid, None)
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ValueError(f"unknown client op {op!r}")
        return handler(session, req)

    def _op_connect(self, session, req) -> dict:
        info = {
            "ok": True,
            "num_clients": len(self._sessions),
            "resources": self._ray.cluster_resources(),
        }
        return info

    def _op_disconnect(self, session, req) -> dict:
        # release happens via identity lookup in _drop_session
        for identity, s in list(self._sessions.items()):
            if s is session:
                self._drop_session(identity)
        return {"ok": True}

    def _op_put(self, session, req) -> dict:
        value = self._resolve_markers(session, C.loads(req["value"]))
        ref = self._ray.put(value)
        return {"ok": True, "ref_id": self._lease_ref(session, ref)}

    def _op_get(self, session, req) -> dict:
        refs = [session.refs[rid] for rid in req["ref_ids"]]
        vals = self._ray.get(refs, timeout=req.get("timeout"))
        return {"ok": True, "values": C.dumps(vals)}

    def _op_wait(self, session, req) -> dict:
        by_id = {session.refs[rid]: rid for rid in req["ref_ids"]}
        ready, pending = self._ray.wait(
            list(by_id.keys()), num_returns=req.get("num_returns", 1),
            timeout=req.get("timeout"))
        return {"ok": True,
                "ready": [by_id[r] for r in ready],
                "pending": [by_id[r] for r in pending]}

    def _op_release(self, session, req) -> dict:
        for rid in req["ref_ids"]:
            session.refs.pop(rid, None)
        return {"ok": True}

    def _op_release_actor(self, session, req) -> dict:
        session.actors.pop(req["actor_id"], None)
        return {"ok": True}

    def _op_register_fn(self, session, req) -> dict:
        fn = C.loads(req["func"])
        opts = req.get("options") or {}
        fn_id = self._mint()
        session.functions[fn_id] = self._ray.remote(**opts)(fn) \
            if opts else self._ray.remote(fn)
        return {"ok": True, "fn_id": fn_id}

    def _op_call_fn(self, session, req) -> dict:
        rf = session.functions[req["fn_id"]]
        if req.get("options"):
            rf = rf.options(**req["options"])
        args, kwargs = self._resolve_markers(
            session, C.loads(req["args"]))
        refs = rf.remote(*args, **kwargs)
        many = isinstance(refs, list)
        out = [self._lease_ref(session, r)
               for r in (refs if many else [refs])]
        return {"ok": True, "ref_ids": out, "many": many}

    def _op_register_class(self, session, req) -> dict:
        cls = C.loads(req["cls"])
        opts = req.get("options") or {}
        cls_id = self._mint()
        session.classes[cls_id] = self._ray.remote(**opts)(cls) \
            if opts else self._ray.remote(cls)
        methods = [n for n in dir(cls)
                   if not n.startswith("_") and callable(getattr(cls, n))]
        return {"ok": True, "cls_id": cls_id, "methods": methods}

    def _op_create_actor(self, session, req) -> dict:
        ac = session.classes[req["cls_id"]]
        opts = req.get("options") or {}
        if opts:
            ac = ac.options(**opts)
        args, kwargs = self._resolve_markers(
            session, C.loads(req["args"]))
        handle = ac.remote(*args, **kwargs)
        if opts.get("lifetime") == "detached" or opts.get("name"):
            handle._detached = True
        aid = self._mint()
        session.actors[aid] = handle
        return {"ok": True, "actor_id": aid}

    def _op_call_method(self, session, req) -> dict:
        handle = session.actors[req["actor_id"]]
        method = getattr(handle, req["method"])
        if req.get("options"):
            method = method.options(**req["options"])
        args, kwargs = self._resolve_markers(
            session, C.loads(req["args"]))
        refs = method.remote(*args, **kwargs)
        many = isinstance(refs, list)
        out = [self._lease_ref(session, r)
               for r in (refs if many else [refs])]
        return {"ok": True, "ref_ids": out, "many": many}

    def _op_get_actor(self, session, req) -> dict:
        handle = self._ray.get_actor(
            req["name"], namespace=req.get("namespace", ""))
        handle._detached = True   # named: outlives this client
        methods = [n for n in dir(handle)
                   if not n.startswith("_")]
        aid = self._mint()
        session.actors[aid] = handle
        # handle exposes methods dynamically; ask the actor class
        return {"ok": True, "actor_id": aid,
                "methods": getattr(handle, "_method_names", methods)}

    def _op_kill_actor(self, session, req) -> dict:
        handle = session.actors.get(req["actor_id"])
        if handle is not None:
            self._ray.kill(handle, no_restart=req.get("no_restart", True))
        return {"ok": True}

    def _op_cancel(self, session, req) -> dict:
        ref = session.refs.get(req["ref_id"])
        if ref is not None:
            self._ray.cancel(ref, force=req.get("force", False))
        return {"ok": True}

    def _op_cluster_info(self, session, req) -> dict:
        kind = req.get("kind", "resources")
        if kind == "resources":
            data = self._ray.cluster_resources()
        elif kind == "available":
            data = self._ray.available_resources()
        elif kind == "nodes":
            data = self._ray.nodes()
        else:
            raise ValueError(f"unknown cluster_info kind {kind!r}")
        return {"ok": True, "data": C.dumps(data)}
