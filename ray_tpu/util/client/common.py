"""Shared wire bits for the client protocol.

One request/one reply, both a pickled dict. Requests carry ``op`` plus
op-specific fields; replies carry ``ok`` and either a result payload or
``error`` (a pickled exception re-raised client-side). ObjectRefs and
actor handles never cross the wire as live objects — they travel as
opaque ids minted by the server and are wrapped client-side.
"""

from __future__ import annotations

import pickle
from typing import Any

import cloudpickle

DEFAULT_PORT = 10001

#: Max time one server-side get/wait handler may block before replying
#: "pending"; the client re-polls in the same slice. Shared here because
#: the two sides must stay in lockstep: the client's per-RPC deadline
#: must comfortably exceed this server-side clamp.
BLOCK_SLICE_S = 2.0


def dumps(obj: Any) -> bytes:
    return cloudpickle.dumps(obj, protocol=5)


def loads(blob: bytes) -> Any:
    return pickle.loads(blob)


class ClientObjectRef:
    """Client-side stand-in for a server-held ObjectRef."""

    __slots__ = ("_id", "_worker", "__weakref__")

    def __init__(self, ref_id: bytes, worker=None):
        self._id = ref_id
        self._worker = worker

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ClientObjectRef({self._id.hex()[:16]})"

    def __del__(self):
        w = self._worker
        if w is not None:
            try:
                w._release(self._id)
            except Exception:
                pass

    def __reduce__(self):
        # travels to the server (inside args) as a marker
        return (_RefMarker, (self._id,))


class _RefMarker:
    """What a ClientObjectRef pickles into: the server swaps it for the
    real ObjectRef it holds for this connection."""

    __slots__ = ("ref_id",)

    def __init__(self, ref_id: bytes):
        self.ref_id = ref_id


class ClientActorHandle:
    """Client-side actor handle: method calls become CALL_METHOD RPCs."""

    def __init__(self, actor_ref_id: bytes, worker, methods):
        self._id = actor_ref_id
        self._worker = worker
        self._methods = set(methods)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._methods:
            raise AttributeError(
                f"actor has no method {name!r} (methods: "
                f"{sorted(self._methods)})")
        return _ClientMethod(self, name)

    def __repr__(self):
        return f"ClientActorHandle({self._id.hex()[:12]})"

    def __del__(self):
        w = getattr(self, "_worker", None)
        if w is not None:
            try:
                w._release_actor(self._id)
            except Exception:
                pass


class _ClientMethod:
    __slots__ = ("_handle", "_name", "_opts")

    def __init__(self, handle, name, opts=None):
        self._handle = handle
        self._name = name
        self._opts = opts or {}

    def options(self, **opts):
        return _ClientMethod(self._handle, self._name, opts)

    def remote(self, *args, **kwargs):
        w = self._handle._worker
        return w._call_method(self._handle._id, self._name, args, kwargs,
                              self._opts)
