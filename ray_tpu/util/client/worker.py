"""Client-side of the client protocol.

Reference: ``python/ray/util/client/worker.py:81`` (``Worker`` — the
gRPC stub behind ``ray.init("ray://...")``) and ``api.py`` (the ClientAPI
that the public functions delegate to in client mode). Here
:class:`ClientWorker` is installed by ``ray_tpu.init("ray://host:port")``;
``ray_tpu.remote/get/put/...`` route to it while connected.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import zmq

from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.util.client import common as C
from ray_tpu.util.client.common import (
    ClientActorHandle, ClientObjectRef)

_BLOCK_SLICE_S = C.BLOCK_SLICE_S

_UNSET = object()


class ClientRemoteFunction:
    """Client counterpart of RemoteFunction: lazily registered with the
    server on first use (ships the cloudpickled function once)."""

    def __init__(self, worker: "ClientWorker", func, options: dict):
        self._worker = worker
        self._func = func
        self._options = dict(options)
        self._fn_id: Optional[bytes] = None
        self.__name__ = getattr(func, "__name__", "anonymous")

    def options(self, **opts):
        merged = dict(self._options)
        merged.update(opts)
        out = ClientRemoteFunction(self._worker, self._func, merged)
        out._fn_id = self._fn_id  # per-call opts ride the CALL message
        out._call_opts = opts
        return out

    def remote(self, *args, **kwargs):
        if self._fn_id is None:
            self._fn_id = self._worker._register_fn(
                self._func, self._options)
        return self._worker._call_fn(
            self._fn_id, args, kwargs, getattr(self, "_call_opts", None))

    def __call__(self, *a, **kw):
        raise TypeError(
            "remote function cannot be called directly; use .remote()")


class ClientActorClass:
    def __init__(self, worker: "ClientWorker", cls, options: dict):
        self._worker = worker
        self._cls = cls
        self._options = dict(options)
        self._cls_id: Optional[bytes] = None
        self._methods: List[str] = []

    def options(self, **opts):
        merged = dict(self._options)
        merged.update(opts)
        out = ClientActorClass(self._worker, self._cls, merged)
        out._cls_id = self._cls_id
        out._methods = self._methods
        out._create_opts = opts
        return out

    def remote(self, *args, **kwargs):
        if self._cls_id is None:
            self._cls_id, self._methods = self._worker._register_class(
                self._cls, self._options)
        opts = getattr(self, "_create_opts", None)
        return self._worker._create_actor(
            self._cls_id, args, kwargs, opts, self._methods)


class ClientWorker:
    """Connection to a ClientServer; implements the public API surface."""

    def __init__(self, address: str, timeout: float = 30.0):
        # address: "ray://host:port"
        hostport = address[len("ray://"):] if address.startswith("ray://") \
            else address
        if ":" not in hostport:
            hostport = f"{hostport}:{C.DEFAULT_PORT}"
        self.address = hostport
        self.timeout = timeout
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.connect(f"tcp://{hostport}")
        self._lock = threading.Lock()   # one in-flight request at a time
        self._rid = 0
        self._closed = False
        # Deferred releases: __del__ may run on any thread, including one
        # already inside _request holding self._lock — so a release NEVER
        # does network I/O itself; it only appends here, and the list is
        # flushed as a piggyback on the next normal request (same pattern
        # as core.reference_counter._deferred_decrefs).
        self._release_lock = threading.Lock()
        self._pending_release: List[bytes] = []
        self._pending_release_actors: List[bytes] = []
        info = self._request({"op": "connect"})
        self.server_info = info

    # -------------------------------------------------------------- rpc
    def _request(self, req: dict, timeout: Any = _UNSET) -> dict:
        """One round-trip. ``timeout`` is the per-RPC reply deadline
        (default: the connection timeout); ``None`` waits forever.
        Blocking ops (get/wait) never need a long RPC deadline — the
        server clamps them to _BLOCK_SLICE_S and the caller re-polls."""
        if self._closed:
            raise ConnectionError("client connection is closed")
        timeout = self.timeout if timeout is _UNSET else timeout
        with self._lock:
            self._rid += 1
            req["rid"] = self._rid
            with self._release_lock:
                rel, self._pending_release = self._pending_release, []
                rel_a, self._pending_release_actors = \
                    self._pending_release_actors, []
            if rel:
                # piggyback deferred ref releases (no extra roundtrip)
                req["release"] = rel
            if rel_a:
                req["release_actors"] = rel_a
            self._sock.send(C.dumps(req))
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while True:
                if deadline is None:
                    wait_ms = 60000
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"client request {req['op']} timed out "
                            f"({timeout}s) against {self.address}")
                    wait_ms = max(1, int(remaining * 1000))
                if not self._sock.poll(wait_ms):
                    continue
                out = C.loads(self._sock.recv())
                if out.get("rid") == self._rid:
                    break
        if not out.get("ok"):
            err = out.get("error")
            raise C.loads(err) if err is not None else \
                ConnectionError("client request failed")
        return out

    def _release(self, ref_id: bytes) -> None:
        # called from __del__ on an arbitrary thread: append only —
        # any network I/O here can deadlock on self._lock (see ctor).
        if self._closed:
            return
        with self._release_lock:
            self._pending_release.append(ref_id)

    def _release_actor(self, actor_id: bytes) -> None:
        # also reached from ClientActorHandle.__del__: defer identically.
        if self._closed:
            return
        with self._release_lock:
            self._pending_release_actors.append(actor_id)

    # -------------------------------------------------------------- api
    def put(self, value: Any) -> ClientObjectRef:
        out = self._request({"op": "put", "value": C.dumps(value)})
        return ClientObjectRef(out["ref_id"], self)

    def get(self, refs, timeout: Optional[float] = None):
        """Blocks until the objects are ready (timeout=None means
        forever, matching the driver-side contract) by re-polling the
        server in _BLOCK_SLICE_S slices — no RPC ever outlives a slice,
        so a long-running task cannot trip the connection timeout."""
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        for r in refs:
            if not isinstance(r, ClientObjectRef):
                raise TypeError(f"expected ClientObjectRef, got {type(r)}")
        ids = [r.binary() for r in refs]
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            sl = _BLOCK_SLICE_S if deadline is None else \
                max(0.0, min(_BLOCK_SLICE_S,
                             deadline - time.monotonic()))
            # RPC deadline: the reply for a ready object includes its
            # serialized value, which can take arbitrarily long to build
            # and transfer for huge objects — so a user-unbounded get
            # gets an unbounded RPC too (contract: get(timeout=None)
            # blocks forever), while a bounded get allows the user's
            # whole remaining budget plus a transfer margin.
            rpc_t = None if timeout is None else \
                max(deadline - time.monotonic(), sl) + \
                max(self.timeout, _BLOCK_SLICE_S * 2)
            out = self._request({"op": "get", "ref_ids": ids,
                                 "timeout": sl}, timeout=rpc_t)
            if not out.get("pending"):
                vals = C.loads(out["values"])
                return vals[0] if single else vals
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(
                    f"ray.get timed out after {timeout}s waiting for "
                    f"{len(ids)} object(s)")

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True
             ) -> Tuple[List[ClientObjectRef], List[ClientObjectRef]]:
        by_id = {r.binary(): r for r in refs}
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            sl = _BLOCK_SLICE_S if deadline is None else \
                max(0.0, min(_BLOCK_SLICE_S,
                             deadline - time.monotonic()))
            out = self._request(
                {"op": "wait", "ref_ids": list(by_id.keys()),
                 "num_returns": num_returns, "timeout": sl},
                timeout=sl + max(self.timeout, _BLOCK_SLICE_S * 2))
            if len(out["ready"]) >= num_returns or (
                    deadline is not None
                    and time.monotonic() >= deadline):
                return ([by_id[b] for b in out["ready"]],
                        [by_id[b] for b in out["pending"]])

    def remote(self, *args, **options):
        if len(args) == 1 and callable(args[0]) and not options:
            return self._wrap(args[0], {})
        def deco(obj):
            return self._wrap(obj, options)
        return deco

    def _wrap(self, obj, options: dict):
        if isinstance(obj, type):
            return ClientActorClass(self, obj, options)
        return ClientRemoteFunction(self, obj, options)

    def kill(self, actor: ClientActorHandle, *, no_restart: bool = True):
        self._request({"op": "kill_actor", "actor_id": actor._id,
                       "no_restart": no_restart})

    def cancel(self, ref: ClientObjectRef, *, force: bool = False):
        self._request({"op": "cancel", "ref_id": ref.binary(),
                       "force": force})

    def get_actor(self, name: str, namespace: str = "") -> ClientActorHandle:
        out = self._request({"op": "get_actor", "name": name,
                             "namespace": namespace})
        return ClientActorHandle(out["actor_id"], self, out["methods"])

    def cluster_resources(self) -> Dict[str, float]:
        return C.loads(self._request(
            {"op": "cluster_info", "kind": "resources"})["data"])

    def available_resources(self) -> Dict[str, float]:
        return C.loads(self._request(
            {"op": "cluster_info", "kind": "available"})["data"])

    def nodes(self) -> List[dict]:
        return C.loads(self._request(
            {"op": "cluster_info", "kind": "nodes"})["data"])

    # ---------------------------------------------------- fn/actor plumbing
    def _register_fn(self, func, options: dict) -> bytes:
        return self._request({"op": "register_fn", "func": C.dumps(func),
                              "options": options})["fn_id"]

    def _call_fn(self, fn_id: bytes, args, kwargs, options):
        out = self._request({
            "op": "call_fn", "fn_id": fn_id,
            "args": C.dumps((args, kwargs)), "options": options})
        refs = [ClientObjectRef(b, self) for b in out["ref_ids"]]
        return refs if out["many"] else refs[0]

    def _register_class(self, cls, options: dict):
        out = self._request({"op": "register_class", "cls": C.dumps(cls),
                             "options": options})
        return out["cls_id"], out["methods"]

    def _create_actor(self, cls_id: bytes, args, kwargs, options, methods):
        out = self._request({
            "op": "create_actor", "cls_id": cls_id,
            "args": C.dumps((args, kwargs)), "options": options})
        return ClientActorHandle(out["actor_id"], self, methods)

    def _call_method(self, actor_id: bytes, method: str, args, kwargs,
                     options):
        out = self._request({
            "op": "call_method", "actor_id": actor_id, "method": method,
            "args": C.dumps((args, kwargs)), "options": options or None})
        refs = [ClientObjectRef(b, self) for b in out["ref_ids"]]
        return refs if out["many"] else refs[0]

    def disconnect(self) -> None:
        if self._closed:
            return
        try:
            self._request({"op": "disconnect"}, timeout=5)
        except Exception:
            pass
        self._closed = True
        try:
            self._sock.close(0)
        except Exception:
            pass

    # duck-type used by api.shutdown
    def shutdown(self) -> None:
        self.disconnect()

    def is_connected(self) -> bool:
        return not self._closed


def connect(address: str, timeout: Optional[float] = None) -> ClientWorker:
    """Connect to a ClientServer; returns the installed ClientWorker.

    ``timeout`` is the per-RPC reply deadline (not a cap on how long
    get/wait may block — those re-poll in slices). Defaults to the
    RAY_TPU_CLIENT_TIMEOUT env var, else 30s."""
    if timeout is None:
        timeout = float(os.environ.get("RAY_TPU_CLIENT_TIMEOUT", "30"))
    return ClientWorker(address, timeout=timeout)
