"""Client-side of the client protocol.

Reference: ``python/ray/util/client/worker.py:81`` (``Worker`` — the
gRPC stub behind ``ray.init("ray://...")``) and ``api.py`` (the ClientAPI
that the public functions delegate to in client mode). Here
:class:`ClientWorker` is installed by ``ray_tpu.init("ray://host:port")``;
``ray_tpu.remote/get/put/...`` route to it while connected.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import zmq

from ray_tpu.util.client import common as C
from ray_tpu.util.client.common import (
    ClientActorHandle, ClientObjectRef)


class ClientRemoteFunction:
    """Client counterpart of RemoteFunction: lazily registered with the
    server on first use (ships the cloudpickled function once)."""

    def __init__(self, worker: "ClientWorker", func, options: dict):
        self._worker = worker
        self._func = func
        self._options = dict(options)
        self._fn_id: Optional[bytes] = None
        self.__name__ = getattr(func, "__name__", "anonymous")

    def options(self, **opts):
        merged = dict(self._options)
        merged.update(opts)
        out = ClientRemoteFunction(self._worker, self._func, merged)
        out._fn_id = self._fn_id  # per-call opts ride the CALL message
        out._call_opts = opts
        return out

    def remote(self, *args, **kwargs):
        if self._fn_id is None:
            self._fn_id = self._worker._register_fn(
                self._func, self._options)
        return self._worker._call_fn(
            self._fn_id, args, kwargs, getattr(self, "_call_opts", None))

    def __call__(self, *a, **kw):
        raise TypeError(
            "remote function cannot be called directly; use .remote()")


class ClientActorClass:
    def __init__(self, worker: "ClientWorker", cls, options: dict):
        self._worker = worker
        self._cls = cls
        self._options = dict(options)
        self._cls_id: Optional[bytes] = None
        self._methods: List[str] = []

    def options(self, **opts):
        merged = dict(self._options)
        merged.update(opts)
        out = ClientActorClass(self._worker, self._cls, merged)
        out._cls_id = self._cls_id
        out._methods = self._methods
        out._create_opts = opts
        return out

    def remote(self, *args, **kwargs):
        if self._cls_id is None:
            self._cls_id, self._methods = self._worker._register_class(
                self._cls, self._options)
        opts = getattr(self, "_create_opts", None)
        return self._worker._create_actor(
            self._cls_id, args, kwargs, opts, self._methods)


class ClientWorker:
    """Connection to a ClientServer; implements the public API surface."""

    def __init__(self, address: str, timeout: float = 30.0):
        # address: "ray://host:port"
        hostport = address[len("ray://"):] if address.startswith("ray://") \
            else address
        if ":" not in hostport:
            hostport = f"{hostport}:{C.DEFAULT_PORT}"
        self.address = hostport
        self.timeout = timeout
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.connect(f"tcp://{hostport}")
        self._lock = threading.Lock()   # one in-flight request at a time
        self._rid = 0
        self._closed = False
        self._pending_release: List[bytes] = []
        info = self._request({"op": "connect"})
        self.server_info = info

    # -------------------------------------------------------------- rpc
    def _request(self, req: dict, timeout: Optional[float] = None) -> dict:
        if self._closed:
            raise ConnectionError("client connection is closed")
        timeout = self.timeout if timeout is None else timeout
        with self._lock:
            self._rid += 1
            req["rid"] = self._rid
            rel, self._pending_release = self._pending_release, []
            if rel:
                # piggyback deferred ref releases (no extra roundtrip)
                req["release"] = rel
            self._sock.send(C.dumps(req))
            deadline = None if timeout is None else timeout * 1000
            while True:
                if not self._sock.poll(deadline if deadline else 60000):
                    raise TimeoutError(
                        f"client request {req['op']} timed out "
                        f"({timeout}s) against {self.address}")
                out = C.loads(self._sock.recv())
                if out.get("rid") == self._rid:
                    break
        if not out.get("ok"):
            err = out.get("error")
            raise C.loads(err) if err is not None else \
                ConnectionError("client request failed")
        return out

    def _release(self, ref_id: bytes) -> None:
        # called from __del__ — defer to the next request, flush if many
        if self._closed:
            return
        self._pending_release.append(ref_id)
        if len(self._pending_release) >= 64:
            try:
                self._request({"op": "release", "ref_ids": []})
            except Exception:
                pass

    def _release_actor(self, actor_id: bytes) -> None:
        if self._closed:
            return
        try:
            self._request({"op": "release_actor", "actor_id": actor_id})
        except Exception:
            pass

    # -------------------------------------------------------------- api
    def put(self, value: Any) -> ClientObjectRef:
        out = self._request({"op": "put", "value": C.dumps(value)})
        return ClientObjectRef(out["ref_id"], self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        for r in refs:
            if not isinstance(r, ClientObjectRef):
                raise TypeError(f"expected ClientObjectRef, got {type(r)}")
        out = self._request(
            {"op": "get", "ref_ids": [r.binary() for r in refs],
             "timeout": timeout},
            timeout=None if timeout is None else timeout + 10)
        vals = C.loads(out["values"])
        return vals[0] if single else vals

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True
             ) -> Tuple[List[ClientObjectRef], List[ClientObjectRef]]:
        by_id = {r.binary(): r for r in refs}
        out = self._request(
            {"op": "wait", "ref_ids": list(by_id.keys()),
             "num_returns": num_returns, "timeout": timeout},
            timeout=None if timeout is None else timeout + 10)
        return ([by_id[b] for b in out["ready"]],
                [by_id[b] for b in out["pending"]])

    def remote(self, *args, **options):
        if len(args) == 1 and callable(args[0]) and not options:
            return self._wrap(args[0], {})
        def deco(obj):
            return self._wrap(obj, options)
        return deco

    def _wrap(self, obj, options: dict):
        if isinstance(obj, type):
            return ClientActorClass(self, obj, options)
        return ClientRemoteFunction(self, obj, options)

    def kill(self, actor: ClientActorHandle, *, no_restart: bool = True):
        self._request({"op": "kill_actor", "actor_id": actor._id,
                       "no_restart": no_restart})

    def cancel(self, ref: ClientObjectRef, *, force: bool = False):
        self._request({"op": "cancel", "ref_id": ref.binary(),
                       "force": force})

    def get_actor(self, name: str, namespace: str = "") -> ClientActorHandle:
        out = self._request({"op": "get_actor", "name": name,
                             "namespace": namespace})
        return ClientActorHandle(out["actor_id"], self, out["methods"])

    def cluster_resources(self) -> Dict[str, float]:
        return C.loads(self._request(
            {"op": "cluster_info", "kind": "resources"})["data"])

    def available_resources(self) -> Dict[str, float]:
        return C.loads(self._request(
            {"op": "cluster_info", "kind": "available"})["data"])

    def nodes(self) -> List[dict]:
        return C.loads(self._request(
            {"op": "cluster_info", "kind": "nodes"})["data"])

    # ---------------------------------------------------- fn/actor plumbing
    def _register_fn(self, func, options: dict) -> bytes:
        return self._request({"op": "register_fn", "func": C.dumps(func),
                              "options": options})["fn_id"]

    def _call_fn(self, fn_id: bytes, args, kwargs, options):
        out = self._request({
            "op": "call_fn", "fn_id": fn_id,
            "args": C.dumps((args, kwargs)), "options": options})
        refs = [ClientObjectRef(b, self) for b in out["ref_ids"]]
        return refs if out["many"] else refs[0]

    def _register_class(self, cls, options: dict):
        out = self._request({"op": "register_class", "cls": C.dumps(cls),
                             "options": options})
        return out["cls_id"], out["methods"]

    def _create_actor(self, cls_id: bytes, args, kwargs, options, methods):
        out = self._request({
            "op": "create_actor", "cls_id": cls_id,
            "args": C.dumps((args, kwargs)), "options": options})
        return ClientActorHandle(out["actor_id"], self, methods)

    def _call_method(self, actor_id: bytes, method: str, args, kwargs,
                     options):
        out = self._request({
            "op": "call_method", "actor_id": actor_id, "method": method,
            "args": C.dumps((args, kwargs)), "options": options or None})
        refs = [ClientObjectRef(b, self) for b in out["ref_ids"]]
        return refs if out["many"] else refs[0]

    def disconnect(self) -> None:
        if self._closed:
            return
        try:
            self._request({"op": "disconnect"}, timeout=5)
        except Exception:
            pass
        self._closed = True
        try:
            self._sock.close(0)
        except Exception:
            pass

    # duck-type used by api.shutdown
    def shutdown(self) -> None:
        self.disconnect()

    def is_connected(self) -> bool:
        return not self._closed


def connect(address: str, timeout: float = 30.0) -> ClientWorker:
    """Connect to a ClientServer; returns the installed ClientWorker."""
    return ClientWorker(address, timeout=timeout)
