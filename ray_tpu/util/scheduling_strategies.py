"""User-facing scheduling strategies (reference:
``python/ray/util/scheduling_strategies.py`` :15/:41/:135)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import NodeID
from ray_tpu.core.task_spec import SchedulingStrategy


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="NODE_AFFINITY",
                                  node_id=NodeID.from_hex(self.node_id),
                                  soft=self.soft)


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[Dict[str, List[str]]] = None,
                 soft: Optional[Dict[str, List[str]]] = None):
        self.hard = hard or {}
        self.soft = soft or {}

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="NODE_LABEL", hard_labels=self.hard,
                                  soft_labels=self.soft)


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group, placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(
            kind="PLACEMENT_GROUP",
            placement_group_id=self.placement_group.id,
            placement_group_bundle_index=self.placement_group_bundle_index,
            placement_group_capture_child_tasks=self.placement_group_capture_child_tasks,
        )
