"""Profiling hooks: CPU stack sampling + memory profiling.

Reference: ``dashboard/modules/reporter/profile_manager.py`` —
``CpuProfilingManager`` shells out to py-spy (:79) and
``MemoryProfilingManager`` to memray (:188) against a worker PID.
Here the same surface: py-spy/memray are used when present (not baked
into the hermetic TPU image); for the common "what is this process
doing" case a built-in pure-Python sampler profiles any process that
hosts a ray_tpu runtime (sampling ``sys._current_frames`` — no
external tooling, works on the idle-host CI)."""

from __future__ import annotations

import collections
import shutil
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple


def pyspy_available() -> bool:
    return shutil.which("py-spy") is not None


def memray_available() -> bool:
    try:
        import memray  # noqa: F401
        return True
    except ImportError:
        return False


def cpu_profile(pid: int, duration_s: float = 5.0,
                output_format: str = "flamegraph") -> bytes:
    """py-spy profile of an arbitrary PID (reference:
    ``profile_manager.py:79``). Gated: raises if py-spy is absent."""
    if not pyspy_available():
        raise RuntimeError(
            "py-spy is not installed in the hermetic TPU image; add it "
            "to the image, or use sample_self() for in-process sampling")
    fmt = {"flamegraph": "flamegraph", "speedscope": "speedscope",
           "raw": "raw"}[output_format]
    out = subprocess.run(
        ["py-spy", "record", "-p", str(pid), "-d", str(int(duration_s)),
         "-f", fmt, "-o", "/dev/stdout"],
        capture_output=True, timeout=duration_s + 30)
    if out.returncode != 0:
        raise RuntimeError(f"py-spy failed: {out.stderr.decode()[-500:]}")
    return out.stdout


def memory_profile(pid: int, duration_s: float = 5.0) -> bytes:
    """memray attach (reference: ``profile_manager.py:188``)."""
    if not memray_available():
        raise RuntimeError(
            "memray is not installed in the hermetic TPU image; add it "
            "to the image to enable memory profiling")
    out = subprocess.run(
        [sys.executable, "-m", "memray", "attach", str(pid),
         "--duration", str(int(duration_s))],
        capture_output=True, timeout=duration_s + 30)
    if out.returncode != 0:
        raise RuntimeError(f"memray failed: {out.stderr.decode()[-500:]}")
    return out.stdout


class StackSampler:
    """In-process sampling profiler over ``sys._current_frames()``:
    the zero-dependency fallback for "where is the time going" on any
    thread of this process. Produces collapsed-stack lines (the
    flamegraph.pl / speedscope-importable format)."""

    def __init__(self, interval_s: float = 0.01):
        self.interval_s = interval_s
        self._counts: Dict[Tuple[str, ...], int] = collections.Counter()
        self._nsamples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StackSampler":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="stack-sampler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack: List[str] = []
                f = frame
                while f is not None and len(stack) < 64:
                    code = f.f_code
                    stack.append(
                        f"{code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{code.co_name}")
                    f = f.f_back
                self._counts[tuple(reversed(stack))] += 1
            self._nsamples += 1

    def stop(self) -> "StackSampler":
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        return self

    @property
    def num_samples(self) -> int:
        return self._nsamples

    def collapsed(self) -> str:
        """One 'frame;frame;frame count' line per unique stack."""
        return "\n".join(
            ";".join(stack) + f" {n}"
            for stack, n in sorted(self._counts.items(),
                                   key=lambda kv: -kv[1]))

    def top(self, k: int = 10) -> List[Tuple[str, int]]:
        """Hottest leaf frames."""
        leaves: Dict[str, int] = collections.Counter()
        for stack, n in self._counts.items():
            if stack:
                leaves[stack[-1]] += n
        return sorted(leaves.items(), key=lambda kv: -kv[1])[:k]


def sample_self(duration_s: float = 1.0,
                interval_s: float = 0.01) -> StackSampler:
    """Convenience: sample this process for ``duration_s``."""
    s = StackSampler(interval_s).start()
    time.sleep(duration_s)
    return s.stop()
