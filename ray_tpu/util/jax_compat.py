"""Compatibility shims across supported JAX versions.

The numerics layer targets current JAX (``jax.shard_map`` with
``check_vma``); older still-deployed versions only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` spelling
of the same flag. Routing every call site through :func:`shard_map`
keeps one code path working on both.
"""

from __future__ import annotations

from typing import Any, Optional


def shard_map(f, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None) -> Any:
    """``jax.shard_map`` where available, else the experimental one
    (``check_vma`` mapped to its old ``check_rep`` name). ``None`` leaves
    the library default."""
    import jax

    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
