"""Actor-backed distributed Queue (reference: ``python/ray/util/queue.py``)."""

from __future__ import annotations

import asyncio
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(num_cpus=0.1)
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = []
        self._maxsize = maxsize

    def qsize(self) -> int:
        return len(self._q)

    def empty(self) -> bool:
        return not self._q

    def full(self) -> bool:
        return self._maxsize > 0 and len(self._q) >= self._maxsize

    def put(self, item) -> bool:
        if self._maxsize > 0 and len(self._q) >= self._maxsize:
            return False
        self._q.append(item)
        return True

    def get(self):
        if not self._q:
            return False, None
        return True, self._q.pop(0)

    def put_batch(self, items: List[Any]) -> int:
        n = 0
        for item in items:
            if self._maxsize > 0 and len(self._q) >= self._maxsize:
                break
            self._q.append(item)
            n += 1
        return n

    def get_batch(self, n: int) -> List[Any]:
        out, self._q = self._q[:n], self._q[n:]
        return out


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item, block: bool = True, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put.remote(item)):
                return
            if not block or (deadline and time.monotonic() >= deadline):
                raise Full()
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote())
            if ok:
                return item
            if not block or (deadline and time.monotonic() >= deadline):
                raise Empty()
            time.sleep(0.01)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        n = ray_tpu.get(self.actor.put_batch.remote(list(items)))
        if n < len(items):
            raise Full()

    def get_nowait_batch(self, n: int) -> List[Any]:
        return ray_tpu.get(self.actor.get_batch.remote(n))

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
