"""State API: queryable live cluster state.

Reference: ``python/ray/util/state/api.py`` (``list_actors/tasks/
objects/nodes/placement_groups/jobs``, ``summarize_*``) served by
``dashboard/state_aggregator.py:141``; here the controller's state
tables answer directly (single control plane, no fan-out needed).
Filters are ``(key, predicate, value)`` tuples with ``=``/``!=``, as in
the reference CLI.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.global_state import global_worker

Filter = Tuple[str, str, Any]


def _query(what: str, filters: Optional[List[Filter]] = None,
           limit: int = 1000, detail: bool = False) -> List[dict]:
    rows = global_worker().state_query(what)
    if not isinstance(rows, list):
        return rows
    for key, op, value in (filters or []):
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported predicate {op!r}")
    return rows[:limit]


def list_nodes(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("nodes", filters, limit)


def list_actors(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("actors", filters, limit)


def list_tasks(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("tasks", filters, limit)


def list_objects(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("objects", filters, limit)


def list_placement_groups(filters=None, limit: int = 1000,
                          **kw) -> List[dict]:
    return _query("placement_groups", filters, limit)


def list_jobs(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("jobs", filters, limit)


def list_workers(filters=None, limit: int = 1000, **kw) -> List[dict]:
    # Workers are surfaced per node (the controller tracks them there).
    out = []
    for n in _query("nodes", None, limit):
        out.append({"node_id": n["node_id"],
                    "num_workers": n["num_workers"]})
    return out


def list_task_events(task_id: Optional[str] = None, filters=None,
                     limit: int = 100_000) -> List[dict]:
    """Merged flight-recorder event stream (core/events.py), oldest
    first. ``task_id`` (hex) narrows to one task's causal timeline;
    ``filters`` apply the standard ``(key, op, value)`` predicates
    (keys: ``ev``, ``proc``, ``trace``, ``span``, ...)."""
    w = global_worker()
    # ship this process's buffered events first so the snapshot
    # includes what the caller just did
    try:
        w.flush_events()
    except Exception:
        pass
    rows = w.state_query("task_events")
    if not isinstance(rows, list):
        return rows
    if task_id is not None:
        rows = [r for r in rows if r.get("task") == task_id]
    for key, op, value in (filters or []):
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported predicate {op!r}")
    return rows[-limit:]


def _fresh_local_report(w) -> None:
    """Ship this process's current metric snapshot ahead of a plane
    query (both ride the same FIFO link, so the report lands first —
    the snapshot the query sees includes what the caller just did)."""
    try:
        w.metrics_reporter.report_now()
    except Exception:
        pass


def list_metrics() -> List[dict]:
    """The fleet metrics catalog (core/metrics_plane.py): one row per
    metric name with type, help text, series count, contributing
    origins, and the fleet total/sum for scalars."""
    w = global_worker()
    _fresh_local_report(w)
    return w.state_query("metrics")


def query_metric(name: str, window_s: float = 60.0,
                 agg: Optional[str] = None) -> Dict[str, Any]:
    """Fleet-aggregated time series for one metric over the trailing
    window (see :meth:`MetricsPlane.query` for the ``agg`` table —
    counter rates, gauge sum/avg/max/min, histogram p50..p99 from
    bucket deltas)."""
    w = global_worker()
    _fresh_local_report(w)
    return w.state_query(
        "metrics_query",
        params={"name": name, "window_s": window_s, "agg": agg})


def fleet_metrics(window_s: float = 30.0) -> Dict[str, Any]:
    """The ``ray-tpu top`` snapshot: per-process rows (tokens/s, queue
    depth, TTFT quantiles, bubble, retransmits, credit stalls) plus
    fleet aggregates."""
    w = global_worker()
    _fresh_local_report(w)
    return w.state_query(
        "metrics_fleet", params={"window_s": window_s})


def list_requests(limit: int = 50) -> List[dict]:
    """Tail-sampled serve request traces at the controller, newest
    first (serve/request_trace.py): one summary row per request —
    request_id, terminal status, duration, SLO trips, and a per-phase
    breakdown. Only slow / failed / 1-in-N requests ship spans, so
    this is the interesting tail, not all traffic."""
    return global_worker().state_query(
        "requests", limit=limit)


def get_request_trace(request_id: str) -> Optional[dict]:
    """Full waterfall for one traced request — every recorded span
    (phase, t0, t1, attrs) sorted by start time, plus SLO trips and
    routing metadata. None when the id never shipped (fast request
    outside the sample, or the trace aged out of the ring)."""
    rows = global_worker().state_query(
        "request_trace", params={"request_id": request_id})
    return rows[0] if rows else None


def summarize_task_latency() -> Dict[str, Any]:
    """Per-task-name latency summary from the flight recorder:
    scheduling delay (SUBMITTED→RUNNING) and execution time
    (RUNNING→FINISHED/FAILED), with count / mean / max in seconds —
    the per-stage signal overlap tuning needs (cf. Podracer /
    MindSpeed RL: rollout→train dataflows are tuned by stage latency,
    not end-to-end wall time)."""
    events = list_task_events()
    per_task: Dict[str, Dict[str, float]] = {}
    names: Dict[str, str] = {}
    for e in events:
        t = e.get("task")
        if t is None:
            continue
        slot = per_task.setdefault(t, {})
        ev = e.get("ev")
        if ev in ("SUBMITTED", "RUNNING", "FINISHED", "FAILED"):
            # first sighting wins for SUBMITTED/RUNNING (replays keep
            # the original submit), last wins for the terminal event
            if ev in ("FINISHED", "FAILED") or ev not in slot:
                slot[ev] = e.get("ts", 0.0)
        if e.get("name"):
            names[t] = e["name"]

    def agg(samples: List[float]) -> Dict[str, float]:
        return {"count": len(samples),
                "mean_s": sum(samples) / len(samples),
                "max_s": max(samples)}

    sched: Dict[str, List[float]] = {}
    execd: Dict[str, List[float]] = {}
    failed: Counter = Counter()
    for t, slot in per_task.items():
        name = names.get(t, "?")
        if "SUBMITTED" in slot and "RUNNING" in slot:
            sched.setdefault(name, []).append(
                max(0.0, slot["RUNNING"] - slot["SUBMITTED"]))
        end = slot.get("FINISHED", slot.get("FAILED"))
        if end is not None and "RUNNING" in slot:
            execd.setdefault(name, []).append(
                max(0.0, end - slot["RUNNING"]))
        if "FAILED" in slot:
            failed[name] += 1
    out: Dict[str, Any] = {}
    for name in sorted(set(sched) | set(execd)):
        out[name] = {}
        if name in sched:
            out[name]["scheduling"] = agg(sched[name])
        if name in execd:
            out[name]["execution"] = agg(execd[name])
        if failed.get(name):
            out[name]["failed"] = failed[name]
    return out


def summarize_tasks() -> Dict[str, Any]:
    by_state: Counter = Counter()
    by_name: Dict[str, Counter] = {}
    for t in list_tasks(limit=100_000):
        state = t.get("state", "UNKNOWN")
        by_state[state] += 1
        name = t.get("name", "?")
        by_name.setdefault(name, Counter())[state] += 1
    return {"total": sum(by_state.values()),
            "by_state": dict(by_state),
            "by_func_name": {k: dict(v) for k, v in by_name.items()}}


def summarize_actors() -> Dict[str, Any]:
    by_state: Counter = Counter()
    for a in list_actors(limit=100_000):
        by_state[a.get("state", "UNKNOWN")] += 1
    return {"total": sum(by_state.values()), "by_state": dict(by_state)}


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects(limit=100_000)
    return {"total": len(objs),
            "total_size_bytes": sum(o.get("size") or 0 for o in objs),
            "inline": sum(1 for o in objs if o.get("inline")),
            "errors": sum(1 for o in objs if o.get("has_error"))}


def get_log(node_id: Optional[str] = None, pid: Optional[int] = None,
            tail: int = 100) -> List[str]:
    """Tail worker logs from the session dir (reference ``get_log``)."""
    import glob
    import os
    w = global_worker()
    session_dir = getattr(w, "session_dir", None)
    if session_dir is None:
        return []
    pattern = os.path.join(session_dir, "logs", "worker-*.out")
    lines: List[str] = []
    import re
    for path in sorted(glob.glob(pattern)):
        if pid is not None:
            nums = re.findall(r"\d+", os.path.basename(path))
            if str(pid) not in nums:
                continue
        with open(path, errors="replace") as f:
            lines.extend(f"{os.path.basename(path)}: {ln.rstrip()}"
                         for ln in f.readlines()[-tail:])
    return lines[-tail:]
