"""State API: queryable live cluster state.

Reference: ``python/ray/util/state/api.py`` (``list_actors/tasks/
objects/nodes/placement_groups/jobs``, ``summarize_*``) served by
``dashboard/state_aggregator.py:141``; here the controller's state
tables answer directly (single control plane, no fan-out needed).
Filters are ``(key, predicate, value)`` tuples with ``=``/``!=``, as in
the reference CLI.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.global_state import global_worker

Filter = Tuple[str, str, Any]


def _query(what: str, filters: Optional[List[Filter]] = None,
           limit: int = 1000, detail: bool = False) -> List[dict]:
    rows = global_worker().state_query(what)
    if not isinstance(rows, list):
        return rows
    for key, op, value in (filters or []):
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"Unsupported predicate {op!r}")
    return rows[:limit]


def list_nodes(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("nodes", filters, limit)


def list_actors(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("actors", filters, limit)


def list_tasks(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("tasks", filters, limit)


def list_objects(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("objects", filters, limit)


def list_placement_groups(filters=None, limit: int = 1000,
                          **kw) -> List[dict]:
    return _query("placement_groups", filters, limit)


def list_jobs(filters=None, limit: int = 1000, **kw) -> List[dict]:
    return _query("jobs", filters, limit)


def list_workers(filters=None, limit: int = 1000, **kw) -> List[dict]:
    # Workers are surfaced per node (the controller tracks them there).
    out = []
    for n in _query("nodes", None, limit):
        out.append({"node_id": n["node_id"],
                    "num_workers": n["num_workers"]})
    return out


def summarize_tasks() -> Dict[str, Any]:
    by_state: Counter = Counter()
    by_name: Dict[str, Counter] = {}
    for t in list_tasks(limit=100_000):
        state = t.get("state", "UNKNOWN")
        by_state[state] += 1
        name = t.get("name", "?")
        by_name.setdefault(name, Counter())[state] += 1
    return {"total": sum(by_state.values()),
            "by_state": dict(by_state),
            "by_func_name": {k: dict(v) for k, v in by_name.items()}}


def summarize_actors() -> Dict[str, Any]:
    by_state: Counter = Counter()
    for a in list_actors(limit=100_000):
        by_state[a.get("state", "UNKNOWN")] += 1
    return {"total": sum(by_state.values()), "by_state": dict(by_state)}


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects(limit=100_000)
    return {"total": len(objs),
            "total_size_bytes": sum(o.get("size") or 0 for o in objs),
            "inline": sum(1 for o in objs if o.get("inline")),
            "errors": sum(1 for o in objs if o.get("has_error"))}


def get_log(node_id: Optional[str] = None, pid: Optional[int] = None,
            tail: int = 100) -> List[str]:
    """Tail worker logs from the session dir (reference ``get_log``)."""
    import glob
    import os
    w = global_worker()
    session_dir = getattr(w, "session_dir", None)
    if session_dir is None:
        return []
    pattern = os.path.join(session_dir, "logs", "worker-*.out")
    lines: List[str] = []
    import re
    for path in sorted(glob.glob(pattern)):
        if pid is not None:
            nums = re.findall(r"\d+", os.path.basename(path))
            if str(pid) not in nums:
                continue
        with open(path, errors="replace") as f:
            lines.extend(f"{os.path.basename(path)}: {ln.rstrip()}"
                         for ln in f.readlines()[-tail:])
    return lines[-tail:]
