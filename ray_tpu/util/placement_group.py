"""Placement groups: gang resource reservations.

Equivalent of the reference's ``python/ray/util/placement_group.py`` over
the GCS placement-group manager (``gcs_placement_group_manager.h:230``,
2-phase commit scheduler ``gcs_placement_group_scheduler.h:419``). For TPU,
a STRICT_PACK group over ``{"TPU": chips_per_host}`` bundles is the unit
that pins a pod slice's hosts.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core import protocol as P
from ray_tpu.core.global_state import global_worker
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.task_spec import Bundle, PlacementGroupSpec


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, state: str = "PENDING",
                 bundle_nodes: Optional[List[bytes]] = None):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self._state = state
        self.bundle_nodes = bundle_nodes or []

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the group is placed (reference returns an ObjectRef;
        a blocking bool keeps the API surface minimal)."""
        if self._state == "CREATED":
            return True
        w = global_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        with w.pg_cond:
            while True:
                ev = w.pg_events.get(self.id.binary())
                if ev and ev.get("state") == "CREATED":
                    self._state = "CREATED"
                    self.bundle_nodes = ev.get("bundle_nodes", [])
                    return True
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                w.pg_cond.wait(timeout=min(0.2, remaining) if remaining else 0.2)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy,
                                 self._state, self.bundle_nodes))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    w = global_worker()
    spec = PlacementGroupSpec(
        pg_id=PlacementGroupID.of(w.job_id),
        bundles=[Bundle(resources=dict(b)) for b in bundles],
        strategy=strategy, name=name, creator_job=w.job_id)
    reply = w.request(P.CREATE_PG, {"spec": spec})
    return PlacementGroup(spec.pg_id, bundles, strategy,
                          state=reply["state"],
                          bundle_nodes=reply.get("bundle_nodes"))


def remove_placement_group(pg: PlacementGroup) -> None:
    global_worker().request(P.REMOVE_PG, {"pg_id": pg.id.binary()})


def placement_group_table() -> List[dict]:
    return global_worker().state_query("placement_groups")
