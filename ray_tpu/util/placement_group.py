"""Placement groups: gang resource reservations.

Equivalent of the reference's ``python/ray/util/placement_group.py`` over
the GCS placement-group manager (``gcs_placement_group_manager.h:230``,
2-phase commit scheduler ``gcs_placement_group_scheduler.h:419``). For TPU,
the slice-spanning strategies are the unit that pins a pod slice's hosts:
``SLICE_SPREAD`` gang-reserves one bundle per DISTINCT host VM of one
slice (pipeline stages / serve replicas each on their own host),
``SLICE_PACK`` packs all bundles onto one slice's hosts. Both reserve
all-or-nothing and stay PENDING until a slice with capacity exists — the
slice autoscaler (``autoscaler/slices.py``) reads pending slice gangs as
whole-slice demand. A drained/preempted slice flips its groups to
RESCHEDULING; ``ready()`` blocks again until a fresh reservation lands.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core import protocol as P
from ray_tpu.core.global_state import global_worker
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.task_spec import Bundle, PlacementGroupSpec


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, state: str = "PENDING",
                 bundle_nodes: Optional[List[bytes]] = None,
                 bundle_labels: Optional[List[Dict[str, str]]] = None):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy
        self._state = state
        self.bundle_nodes = bundle_nodes or []
        #: per-bundle node labels of the current reservation (the
        #: gang → mesh hand-off: carries ``ray-tpu-slice-id``)
        self.bundle_labels = bundle_labels or []

    @property
    def state(self) -> str:
        """Latest known state (CREATED / PENDING / RESCHEDULING)."""
        ev = global_worker().pg_events.get(self.id.binary())
        if ev:
            self._state = ev.get("state", self._state)
        return self._state

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the group is placed (reference returns an ObjectRef;
        a blocking bool keeps the API surface minimal). A group whose
        slice drained re-enters PENDING as RESCHEDULING — ready()
        then blocks again until a fresh gang reservation lands."""
        w = global_worker()
        deadline = None if timeout is None else time.monotonic() + timeout
        with w.pg_cond:
            while True:
                ev = w.pg_events.get(self.id.binary())
                # the latest controller event wins over the cached
                # create-reply state (a RESCHEDULING notice must
                # invalidate an old CREATED)
                state = ev.get("state") if ev else self._state
                self._state = state
                if state == "CREATED":
                    if ev:
                        self.bundle_nodes = ev.get(
                            "bundle_nodes", self.bundle_nodes)
                        self.bundle_labels = ev.get(
                            "bundle_labels", self.bundle_labels)
                    return True
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                w.pg_cond.wait(timeout=min(0.2, remaining) if remaining else 0.2)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    def slice_id(self) -> Optional[str]:
        """The TPU slice hosting this gang, when every placed bundle's
        node carries the same ``ray-tpu-slice-id`` label — the handle a
        driver uses to name the ICI domain its stage meshes share
        (``parallel.plan`` logs it; benches record it). None for loose
        placements or before the gang is placed."""
        from ray_tpu.core.scheduler import node_slice_id
        if not self.bundle_labels:
            return None
        ids = {node_slice_id(labels or {})
               for labels in self.bundle_labels}
        ids.discard(None)
        return ids.pop() if len(ids) == 1 else None

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy,
                                 self._state, self.bundle_nodes,
                                 self.bundle_labels))


#: the strategies the bundle planner implements
#: (core/scheduler.py::_plan_bundles)
STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
              "SLICE_PACK", "SLICE_SPREAD")


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in STRATEGIES:
        raise ValueError(
            f"unknown placement strategy {strategy!r} "
            f"(one of {', '.join(STRATEGIES)})")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    w = global_worker()
    spec = PlacementGroupSpec(
        pg_id=PlacementGroupID.of(w.job_id),
        bundles=[Bundle(resources=dict(b)) for b in bundles],
        strategy=strategy, name=name, creator_job=w.job_id)
    reply = w.request(P.CREATE_PG, {"spec": spec})
    return PlacementGroup(spec.pg_id, bundles, strategy,
                          state=reply["state"],
                          bundle_nodes=reply.get("bundle_nodes"),
                          bundle_labels=reply.get("bundle_labels"))


def remove_placement_group(pg: PlacementGroup) -> None:
    global_worker().request(P.REMOVE_PG, {"pg_id": pg.id.binary()})


def placement_group_table() -> List[dict]:
    return global_worker().state_query("placement_groups")
