"""Distributed tracing: spans around tasks/actors.

Reference: ``python/ray/util/tracing/tracing_helper.py`` — Ray wraps
task submission/execution in OpenTelemetry spans when the user enables
tracing with an exporter. Here the same layering: if ``opentelemetry``
is importable, spans go to its tracer provider; otherwise spans fall
back to the runtime's built-in timeline (``ray-tpu timeline`` renders
them in the Chrome trace), so tracing works out of the box with zero
extra dependencies."""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterator, Optional

_enabled = False
_lock = threading.Lock()


def enable_tracing() -> None:
    """Turn on span recording (reference: ``ray.init(_tracing_startup_
    hook=...)``)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def _otel_tracer():
    """A real OpenTelemetry tracer, or None. The default/proxy/no-op
    provider doesn't count: with no user-configured exporter the spans
    would vanish — the timeline fallback is strictly more useful."""
    try:
        from opentelemetry import trace
    except ImportError:
        return None
    provider = trace.get_tracer_provider()
    kind = type(provider).__name__
    if "NoOp" in kind or "Proxy" in kind or "Default" in kind:
        return None
    return trace.get_tracer("ray_tpu")


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None
         ) -> Iterator[None]:
    """Record one span. OpenTelemetry when available; else the span
    lands in the runtime timeline as a complete event."""
    if not _enabled:
        yield
        return
    tracer = _otel_tracer()
    if tracer is not None:
        with tracer.start_as_current_span(name) as s:
            for k, v in (attributes or {}).items():
                s.set_attribute(k, v)
            yield
        return
    start = time.time()
    try:
        yield
    finally:
        dur = time.time() - start
        from ray_tpu.core.global_state import try_global_worker
        w = try_global_worker()
        if w is not None:
            try:
                w.record_span(name, start, dur, **(attributes or {}))
            except Exception:
                pass
