"""Distributed tracing: spans around tasks/actors.

Reference: ``python/ray/util/tracing/tracing_helper.py`` — Ray wraps
task submission/execution in OpenTelemetry spans when the user enables
tracing with an exporter. Here the same layering: if ``opentelemetry``
is importable AND a real (SDK) tracer provider is configured, spans go
to its tracer; otherwise spans fall back to the runtime's built-in
timeline (``ray-tpu timeline`` renders them in the Chrome trace), so
tracing works out of the box with zero extra dependencies.

Cross-process propagation: a ``span()`` also installs a flight-recorder
trace context (``ray_tpu.core.events``) on the current thread, and the
runtime threads that context through task/actor-call submission
(``TaskSpec.trace``) — so both OpenTelemetry (when configured) and the
built-in timeline show parent→child links across processes. On the
executing side, :func:`task_execution_span` re-parents the task's span
under the propagated remote context.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterator, Optional, Tuple

_enabled = False
_lock = threading.Lock()


def enable_tracing() -> None:
    """Turn on span recording (reference: ``ray.init(_tracing_startup_
    hook=...)``)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def _is_noop_provider(provider) -> bool:
    """True for OpenTelemetry's built-in exporterless providers. Name
    checks are case-insensitive and paired with a module check because
    the API has renamed these classes across releases (``DefaultTracer
    Provider`` → ``NoOpTracerProvider`` in ≥1.25; ``ProxyTracer
    Provider`` proxies to one until an SDK provider is installed): any
    provider defined inside the ``opentelemetry.trace``/``opentelemetry
    .util`` API packages is exporterless by construction — only an SDK
    (or third-party) provider can actually export spans."""
    cls = type(provider)
    mod = getattr(cls, "__module__", "") or ""
    if mod == "opentelemetry.trace" or \
            mod.startswith(("opentelemetry.trace.", "opentelemetry.util")):
        return True
    name = cls.__name__.lower()
    return any(s in name for s in ("noop", "proxy", "default"))


def _otel_tracer():
    """A real OpenTelemetry tracer, or None. The default/proxy/no-op
    provider doesn't count: with no user-configured exporter the spans
    would vanish — the timeline fallback is strictly more useful."""
    try:
        from opentelemetry import trace
    except ImportError:
        return None
    try:
        provider = trace.get_tracer_provider()
    except Exception:
        return None
    if _is_noop_provider(provider):
        return None
    return trace.get_tracer("ray_tpu")


def _otel_ids(span) -> Optional[Tuple[str, str]]:
    """``(trace_id, span_id)`` hex of an OTel span, or None."""
    try:
        ctx = span.get_span_context()
        return (format(ctx.trace_id, "032x"), format(ctx.span_id, "016x"))
    except Exception:
        return None


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None
         ) -> Iterator[None]:
    """Record one span. OpenTelemetry when available; else the span
    lands in the runtime timeline as a complete event. Either way the
    span becomes the current flight-recorder trace context, so tasks
    submitted inside it carry a parent→child link across processes."""
    if not _enabled:
        yield
        return
    from ray_tpu.core import events as EV
    tracer = _otel_tracer()
    if tracer is not None:
        with tracer.start_as_current_span(name) as s:
            for k, v in (attributes or {}).items():
                s.set_attribute(k, v)
            ids = _otel_ids(s)
            token = EV.set_context(*ids) if ids else None
            try:
                yield
            finally:
                if ids:
                    EV.restore(token)
        return
    # built-in fallback: new span id, inherit (or root) the trace id
    cur = EV.current()
    span_id = EV.new_span_id()
    trace_id = cur[0] if cur is not None else span_id * 2
    token = EV.set_context(trace_id, span_id)
    start = time.time()
    try:
        yield
    finally:
        EV.restore(token)
        dur = time.time() - start
        from ray_tpu.core.global_state import try_global_worker
        w = try_global_worker()
        if w is not None:
            try:
                w.record_span(name, start, dur, trace_id=trace_id,
                              span_id=span_id,
                              parent=cur[1] if cur else None,
                              **(attributes or {}))
            except Exception:
                pass


@contextlib.contextmanager
def task_execution_span(name: str, trace: Optional[tuple]
                        ) -> Iterator[None]:
    """Executing-side half of cross-process propagation: when tracing
    is enabled and a real OTel provider is configured, run the task
    body inside a span whose REMOTE parent is the propagated
    ``TaskSpec.trace`` context — OTel backends then render the same
    parent→child links the flight recorder records. No-op (single
    boolean check) when tracing is off."""
    if not _enabled:
        yield
        return
    tracer = _otel_tracer()
    if tracer is None:
        yield
        return
    try:
        from opentelemetry import trace as otrace
        from opentelemetry.trace import (
            NonRecordingSpan, SpanContext, TraceFlags)
        parent_ctx = None
        if trace and trace[0]:
            parent_span = trace[1]
            sc = SpanContext(
                trace_id=int(trace[0][:32].ljust(32, "0"), 16),
                span_id=int((parent_span or trace[0][:16]).ljust(16, "0"),
                            16),
                is_remote=True, trace_flags=TraceFlags(1))
            parent_ctx = otrace.set_span_in_context(NonRecordingSpan(sc))
    except Exception:
        yield
        return
    with tracer.start_as_current_span(name, context=parent_ctx):
        yield
