"""Shared retry backoff: exponential growth with jitter, capped.

One implementation for every retry loop in the tree (reference: the
retry shape used across the GCS client, lease requests, and the cloud
provider transports — ``exponential_backoff.h`` and gcp/node.py's
retriable request path). Two jitter modes:

- ``full``: delay ~ U(0, min(cap, base * 2^attempt)). Best de-correlation
  under contention (AWS architecture blog's "full jitter"); used on the
  lease/reconnect path where many processes can retry against one
  controller at once.
- ``equal``: delay ~ d/2 + U(0, d/2) with d = min(cap, base * 2^attempt).
  Keeps a floor so tests can assert growth windows; used by the TPU API
  client (preserves its historical sleep envelope).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional


def backoff_delay(attempt: int, base: float = 0.5, cap: float = 30.0,
                  jitter: str = "full",
                  rng: Optional[random.Random] = None) -> float:
    """Delay (seconds) for retry number ``attempt`` (0-based)."""
    r = rng if rng is not None else random
    d = min(cap, base * (2.0 ** max(0, attempt)))
    if jitter == "equal":
        return d * 0.5 + r.random() * d * 0.5
    if jitter == "none":
        return d
    return r.random() * d  # full jitter


class ExponentialBackoff:
    """Stateful backoff counter: ``next_delay()`` per failure,
    ``reset()`` on success. Thread-compatible for the single-writer
    patterns it serves (each instance is owned by one retry loop)."""

    def __init__(self, base: float = 0.5, cap: float = 30.0,
                 jitter: str = "full",
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random()
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def next_delay(self) -> float:
        d = backoff_delay(self._attempt, self.base, self.cap,
                          self.jitter, self._rng)
        self._attempt += 1
        return d

    def reset(self) -> None:
        self._attempt = 0

    def sleep(self, sleep_fn: Callable[[float], None] = time.sleep) -> float:
        """Draw the next delay and sleep it; returns the delay."""
        d = self.next_delay()
        sleep_fn(d)
        return d
