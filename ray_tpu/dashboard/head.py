"""Dashboard head: JSON-over-HTTP API in the head process.

Reference: ``python/ray/dashboard/head.py:81`` (aiohttp app aggregating
module routes) + ``modules/job/job_head.py`` (the /api/jobs/ REST
surface the Job SDK talks to). Route shapes match the reference's job
API so a reference SDK user finds the same contract; cluster state comes
straight from the controller's state tables instead of per-node agents.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import urlparse

from ray_tpu.dashboard.job_manager import JobManager

logger = logging.getLogger(__name__)


class DashboardHead:
    def __init__(self, session_dir: str, controller, port: int = 0):
        self.session_dir = session_dir
        self.controller = controller
        self.job_manager = JobManager(session_dir)
        handler = _make_handler(self)
        self.server = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.address = f"http://127.0.0.1:{self.server.server_address[1]}"
        try:
            # discoverable by external clients / the CLI (reference analog:
            # the dashboard URL recorded in the GCS + ray.init() banner);
            # written BEFORE serving so a failure here can't leak a live
            # server with no handle to stop it
            with open(os.path.join(session_dir, "dashboard.json"), "w") as f:
                json.dump({"address": self.address}, f)
        except Exception:
            self.server.server_close()
            raise
        self._thread = threading.Thread(
            target=self.server.serve_forever, name="dashboard-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.job_manager.shutdown()
        try:
            self.server.shutdown()
            self.server.server_close()
        except Exception:
            pass

    # ----------------------------------------------------------- serve
    def serve_controller(self):
        """Handle to the named serve controller actor, or None when
        serve was never started. The head process is the driver, so
        its global worker resolves named actors directly."""
        try:
            import ray_tpu
            from ray_tpu.serve._private.controller import \
                CONTROLLER_NAME
            return ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            return None

    # ----------------------------------------------------- cluster state
    def state(self, what: str, limit: int = 1000):
        """Live state rows for the UI (same snapshot the wire state API
        serves; reference: dashboard state_aggregator over GCS)."""
        return self.controller.call_on_loop(
            lambda: self.controller.state_rows(what, limit))

    def cluster_status(self) -> dict:
        # controller state is single-thread-owned: snapshot on its loop
        return self.controller.call_on_loop(self._cluster_status_locked)

    def _cluster_status_locked(self) -> dict:
        c = self.controller
        nodes = []
        for node in c.nodes.values():
            nodes.append({
                "node_id": node.node_id.hex(),
                "alive": node.alive,
                "resources_total": dict(node.resources.total),
                "resources_available": dict(node.resources.available),
                "num_workers": len(node.all_workers),
            })
        states: dict = {}
        for row in c.task_table.values():
            states[row.get("state", "?")] = \
                states.get(row.get("state", "?"), 0) + 1
        return {
            "nodes": nodes,
            "num_actors": len(c.actors),
            "num_objects": len(c.objects),
            "task_states": states,
            "num_pending_tasks": len(c.tasks),
        }


def _make_handler(head: DashboardHead):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet by default
            logger.debug("dashboard: " + fmt, *args)

        # -- helpers --
        def _json(self, obj: Any, code: int = 200) -> None:
            blob = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _text(self, text: str, code: int = 200) -> None:
            blob = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length") or 0)
            if not n:
                return {}
            return json.loads(self.rfile.read(n) or b"{}")

        def _job_id_from(self, path: str) -> Optional[str]:
            parts = [p for p in path.split("/") if p]
            # /api/jobs/<id>[/logs|/stop]
            return parts[2] if len(parts) >= 3 else None

        def _html(self, text: str) -> None:
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        # -- routes --
        def do_GET(self):
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/")
            try:
                if path in ("", "/index.html"):
                    from ray_tpu.dashboard.static_ui import INDEX_HTML
                    self._html(INDEX_HTML)
                elif path.startswith("/api/state/"):
                    what = path.split("/")[-1]
                    if what not in ("nodes", "actors", "tasks",
                                    "objects", "placement_groups",
                                    "jobs", "node_processes"):
                        self._json({"error": f"unknown state {what!r}"},
                                   404)
                        return
                    from urllib.parse import parse_qs
                    q = parse_qs(parsed.query)
                    try:
                        limit = int(q.get("limit", ["1000"])[0])
                    except ValueError:
                        self._json({"error": "limit must be an int"},
                                   400)
                        return
                    self._json({"rows": head.state(what, limit)})
                elif path == "/metrics":
                    # the single cluster Prometheus scrape target:
                    # every process's samples, labelled by origin
                    # (node/pid/role). MetricsPlane is internally
                    # locked, so no loop marshal is needed.
                    self._text(
                        head.controller.metrics_plane.prometheus_text())
                elif path == "/api/v0/metrics":
                    self._json(
                        {"metrics":
                         head.controller.metrics_plane.catalog()})
                elif path == "/api/v0/metrics/query":
                    # ?name=<metric>&window=<s>&agg=<rate|sum|p99|...>
                    from urllib.parse import parse_qs
                    q = parse_qs(parsed.query)
                    name = (q.get("name") or [""])[0]
                    if not name:
                        self._json({"error": "name query param "
                                    "required"}, 400)
                        return
                    try:
                        window = float((q.get("window") or ["60"])[0])
                    except ValueError:
                        self._json({"error": "window must be a "
                                    "number"}, 400)
                        return
                    agg = (q.get("agg") or [None])[0]
                    try:
                        self._json(head.controller.metrics_plane.query(
                            name, window_s=window, agg=agg))
                    except ValueError as e:
                        self._json({"error": str(e)}, 400)
                elif path == "/api/v0/metrics/fleet":
                    from urllib.parse import parse_qs
                    q = parse_qs(parsed.query)
                    try:
                        window = float((q.get("window") or ["30"])[0])
                    except ValueError:
                        self._json({"error": "window must be a "
                                    "number"}, 400)
                        return
                    self._json(
                        head.controller.metrics_plane.fleet_summary(
                            window_s=window))
                elif path == "/api/timeline":
                    self._json(head.state("timeline", 100_000))
                elif path == "/api/v0/events":
                    # merged flight-recorder stream (core/events.py);
                    # ?task=<hex> narrows to one task, ?ev=<EVENT>
                    # to one event type
                    from urllib.parse import parse_qs
                    q = parse_qs(parsed.query)
                    rows = head.state("task_events", 100_000)
                    task = (q.get("task") or [None])[0]
                    if task:
                        rows = [r for r in rows if r.get("task") == task]
                    ev = (q.get("ev") or [None])[0]
                    if ev:
                        rows = [r for r in rows if r.get("ev") == ev]
                    try:
                        limit = int(q.get("limit", ["100000"])[0])
                    except ValueError:
                        self._json({"error": "limit must be an int"},
                                   400)
                        return
                    self._json({"rows": rows[-limit:]})
                elif path == "/timeline":
                    # Perfetto/Chrome-trace JSON of the flight-recorder
                    # stream: load it at https://ui.perfetto.dev or
                    # chrome://tracing (one track per process, flow
                    # arrows along trace ids). Fleet metric series ride
                    # along as counter tracks ("ph":"C") — tokens/s,
                    # queue depth and occupancy curves next to spans.
                    from ray_tpu.core.events import build_chrome_trace
                    store = head.controller.request_traces
                    self._json(build_chrome_trace(
                        head.state("task_events", 100_000),
                        counters=head.controller.metrics_plane
                        .chrome_counters(),
                        requests=[w for w in (
                            store.waterfall(r["request_id"])
                            for r in store.rows(limit=50))
                            if w is not None]))
                elif path == "/api/v0/requests":
                    # tail-sampled request-trace summaries (slow /
                    # failed / 1-in-N); RequestTraceStore is internally
                    # locked like MetricsPlane, so no loop marshal
                    from urllib.parse import parse_qs
                    q = parse_qs(parsed.query)
                    try:
                        limit = int(q.get("limit", ["50"])[0])
                    except ValueError:
                        self._json({"error": "limit must be an int"},
                                   400)
                        return
                    self._json({"rows": head.controller
                                .request_traces.rows(limit=limit)})
                elif path.startswith("/api/v0/requests/"):
                    # /api/v0/requests/<request_id> -> full waterfall
                    rid = path.rsplit("/", 1)[-1]
                    w = head.controller.request_traces.waterfall(rid)
                    if w is None:
                        self._json(
                            {"error": f"no trace for {rid!r} (fast "
                             "requests outside the tail sample ship "
                             "no spans)"}, 404)
                    else:
                        self._json(w)
                elif path == "/api/jobs":
                    self._json(head.job_manager.list_jobs())
                elif path == "/api/v0/admission/policy":
                    ctrl = head.serve_controller()
                    if ctrl is None:
                        self._json({"error": "no serve controller"},
                                   404)
                        return
                    import ray_tpu
                    seq, policy = ray_tpu.get(
                        ctrl.get_admission_policy.remote())
                    self._json({"seq": seq, "policy": policy})
                elif path == "/api/v0/arbiter":
                    # live slice-arbitration table (who owns which
                    # slice and why); present only when the head runs
                    # with an arbiter: config section
                    arb = getattr(head.controller, "slice_arbiter",
                                  None)
                    if arb is None:
                        self._json({"error": "no slice arbiter "
                                    "configured"}, 404)
                    else:
                        self._json(arb.status())
                elif path == "/api/version":
                    from ray_tpu import __version__
                    self._json({"version": __version__,
                                "ray_tpu_session": head.session_dir})
                elif path == "/api/cluster_status":
                    self._json(head.cluster_status())
                elif path.startswith("/api/nodes/") \
                        and path.endswith("/profile"):
                    # /api/nodes/<node_hex>/profile?worker=<hex>&
                    # duration=2 -> collapsed-stack flamegraph artifact
                    # (reference: reporter agent's on-demand profiling,
                    # profile_manager.py:79)
                    from urllib.parse import parse_qs
                    q = parse_qs(parsed.query)
                    worker_hex = (q.get("worker") or [""])[0]
                    if not worker_hex:
                        self._json(
                            {"error": "worker query param required "
                             "(hex identity from "
                             "/api/state/node_processes)"}, 400)
                        return
                    try:
                        duration = float(
                            (q.get("duration") or ["2"])[0])
                    except ValueError:
                        self._json({"error": "bad duration"}, 400)
                        return
                    result = head.controller.profile_worker(
                        bytes.fromhex(worker_hex),
                        duration_s=min(duration, 30.0))
                    if result is None:
                        self._json({"error": "profile timed out "
                                    "(worker gone?)"}, 504)
                    elif result.get("error"):
                        self._json({"error": result["error"]}, 500)
                    else:
                        self._text(result.get("collapsed") or "")
                elif path.startswith("/api/jobs/") and path.endswith("/logs"):
                    jid = self._job_id_from(path)
                    if head.job_manager.get_job_info(jid) is None:
                        self._json({"error": f"job {jid!r} not found"}, 404)
                    else:
                        self._json(
                            {"logs": head.job_manager.get_job_logs(jid)})
                elif path.startswith("/api/jobs/"):
                    jid = self._job_id_from(path)
                    info = head.job_manager.get_job_info(jid)
                    if info is None:
                        self._json({"error": f"job {jid!r} not found"}, 404)
                    else:
                        self._json(info)
                else:
                    self._json({"error": "not found"}, 404)
            except Exception as e:  # noqa: BLE001
                logger.exception("dashboard GET %s", path)
                self._json({"error": str(e)}, 500)

        def do_POST(self):
            path = urlparse(self.path).path.rstrip("/")
            try:
                if path == "/api/jobs":
                    body = self._body()
                    if not body.get("entrypoint"):
                        self._json({"error": "entrypoint is required"}, 400)
                        return
                    jid = head.job_manager.submit_job(
                        entrypoint=body["entrypoint"],
                        submission_id=body.get("submission_id"),
                        metadata=body.get("metadata"),
                        runtime_env=body.get("runtime_env"),
                        priority=body.get("priority") or "normal",
                        elastic=bool(body.get("elastic")))
                    self._json({"submission_id": jid})
                elif path.startswith("/api/jobs/") and path.endswith("/stop"):
                    jid = self._job_id_from(path)
                    try:
                        stopped = head.job_manager.stop_job(jid)
                        self._json({"stopped": stopped})
                    except KeyError:
                        self._json({"error": f"job {jid!r} not found"}, 404)
                elif path == "/api/v0/admission/policy":
                    # fleet-wide admission budget refresh: validate
                    # here (bad knobs -> 400 via the ValueError
                    # handler below, nothing stored), then push to
                    # the serve controller's config plane; routers
                    # with admission enabled pick it up on their next
                    # rate-limited poll
                    from ray_tpu.serve.admission import AdmissionPolicy
                    body = self._body()
                    policy = AdmissionPolicy.from_dict(body)
                    ctrl = head.serve_controller()
                    if ctrl is None:
                        self._json({"error": "no serve controller "
                                    "(serve not started)"}, 404)
                        return
                    import ray_tpu
                    seq = ray_tpu.get(
                        ctrl.set_admission_policy.remote(
                            policy.to_dict()))
                    self._json({"seq": seq,
                                "policy": policy.to_dict()})
                else:
                    self._json({"error": "not found"}, 404)
            except ValueError as e:
                self._json({"error": str(e)}, 400)
            except Exception as e:  # noqa: BLE001
                logger.exception("dashboard POST %s", path)
                self._json({"error": str(e)}, 500)

    return Handler
