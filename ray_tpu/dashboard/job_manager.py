"""Job manager: run driver scripts against the cluster, track their fate.

Reference: ``python/ray/dashboard/modules/job/job_manager.py:525`` —
JobManager spawns a JobSupervisor actor per job which execs the entrypoint
and monitors it. Here the supervisor is a thread in the head process
supervising the entrypoint subprocess directly: the entrypoint is its own
driver process either way, and a TPU head has no multi-tenant isolation
need that would justify an actor hop. Environment propagation
(RAY_TPU_ADDRESS) makes the child's ``ray_tpu.init()`` connect to this
cluster, like the reference's RAY_ADDRESS injection.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class JobStatus:
    """Reference: ``python/ray/dashboard/modules/job/common.py`` JobStatus."""
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"

    TERMINAL = frozenset({STOPPED, SUCCEEDED, FAILED})


class JobInfo:
    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: Optional[Dict[str, str]] = None,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 priority: str = "normal", elastic: bool = False):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.metadata = metadata or {}
        self.runtime_env = runtime_env or {}
        # arbitration hints: priority orders preemption victims (the
        # SliceArbiter drains the lowest-priority training job's slice
        # first); elastic declares the driver survives losing a slice
        # mid-run (ElasticTrainer re-lowers instead of dying)
        self.priority = priority
        self.elastic = bool(elastic)
        self.status = JobStatus.PENDING
        self.message = ""
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.driver_exit_code: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "submission_id": self.submission_id,
            "entrypoint": self.entrypoint,
            "status": self.status,
            "message": self.message,
            "metadata": self.metadata,
            "runtime_env": {k: v for k, v in self.runtime_env.items()
                            if k != "env_vars"} if self.runtime_env else {},
            "priority": self.priority,
            "elastic": self.elastic,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "driver_exit_code": self.driver_exit_code,
        }


class JobManager:
    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self.log_dir = os.path.join(session_dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- submit
    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   priority: str = "normal",
                   elastic: bool = False) -> str:
        submission_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:12]}"
        if priority not in ("low", "normal", "high"):
            raise ValueError(
                f"priority must be low/normal/high, got {priority!r}")
        with self._lock:
            if submission_id in self._jobs:
                raise ValueError(f"job {submission_id!r} already exists")
            info = JobInfo(submission_id, entrypoint, metadata,
                           runtime_env, priority=priority,
                           elastic=elastic)
            self._jobs[submission_id] = info
        t = threading.Thread(target=self._supervise, args=(info,),
                             name=f"job-supervisor-{submission_id}",
                             daemon=True)
        t.start()
        return submission_id

    def _supervise(self, info: JobInfo) -> None:
        """Per-job supervisor (reference: JobSupervisor.run): exec the
        entrypoint wired to this cluster, stream output to the job log,
        record the terminal status from the exit code."""
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.session_dir
        env["RAY_TPU_JOB_SUBMISSION_ID"] = info.submission_id
        # drivers read these to claim their slices with the arbiter at
        # the right priority (and to decide whether to wrap training in
        # ElasticTrainer)
        env["RAY_TPU_JOB_PRIORITY"] = info.priority
        env["RAY_TPU_JOB_ELASTIC"] = "1" if info.elastic else "0"
        # the entrypoint's driver must find ray_tpu even when the package
        # is run from a source tree (same propagation the node manager
        # does for workers)
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in [pkg_parent, existing] if p)
        renv = info.runtime_env or {}
        for k, v in (renv.get("env_vars") or {}).items():
            env[k] = str(v)
        cwd = renv.get("working_dir") or None
        if cwd is not None and not os.path.isdir(cwd):
            with self._lock:
                info.status = JobStatus.FAILED
                info.message = f"working_dir {cwd!r} does not exist"
                info.end_time = time.time()
            return
        log = open(self.log_path(info.submission_id), "ab")
        try:
            proc = subprocess.Popen(
                info.entrypoint, shell=True, env=env, cwd=cwd,
                stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True)
        except OSError as e:
            with self._lock:
                info.status = JobStatus.FAILED
                info.message = f"failed to start entrypoint: {e}"
                info.end_time = time.time()
            log.close()
            return
        with self._lock:
            # a stop() racing startup wins: kill immediately
            if info.status == JobStatus.STOPPED:
                _terminate(proc)
            else:
                info.status = JobStatus.RUNNING
                info.message = "job is running"
            self._procs[info.submission_id] = proc
        code = proc.wait()
        log.close()
        with self._lock:
            self._procs.pop(info.submission_id, None)
            info.end_time = time.time()
            info.driver_exit_code = code
            if info.status == JobStatus.STOPPED:
                info.message = "job was stopped"
            elif code == 0:
                info.status = JobStatus.SUCCEEDED
                info.message = "job finished successfully"
            else:
                info.status = JobStatus.FAILED
                info.message = f"driver exited with code {code}"

    # -------------------------------------------------------------- query
    def get_job_info(self, submission_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            info = self._jobs.get(submission_id)
            return info.to_dict() if info else None

    def list_jobs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [i.to_dict() for i in self._jobs.values()]

    def log_path(self, submission_id: str) -> str:
        return os.path.join(self.log_dir, f"job-{submission_id}.log")

    def get_job_logs(self, submission_id: str) -> str:
        try:
            with open(self.log_path(submission_id), "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(submission_id)
            if info is None:
                raise KeyError(submission_id)
            if info.status in JobStatus.TERMINAL:
                return False
            info.status = JobStatus.STOPPED
            proc = self._procs.get(submission_id)
        if proc is not None:
            _terminate(proc)
        return True

    def shutdown(self) -> None:
        # SIGTERM everything first, then one shared grace deadline before
        # SIGKILL — shutdown cost stays ~grace_s no matter how many jobs
        with self._lock:
            procs = list(self._procs.values())
        for p in procs:
            try:
                os.killpg(p.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError, OSError):
                pass
        deadline = time.monotonic() + 3.0
        for p in procs:
            while time.monotonic() < deadline and p.poll() is None:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    pass


def _terminate(proc: subprocess.Popen, grace_s: float = 3.0) -> None:
    """SIGTERM the entrypoint's process group, escalate to SIGKILL
    (reference: JobSupervisor.stop's polite-then-forceful kill)."""
    try:
        os.killpg(proc.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass
