"""The dashboard's single-page UI: vanilla HTML/JS over the REST API.

Reference: ``dashboard/client/`` (a 21.7k-LoC React app). Scope here is
the operator's tables — cluster summary, nodes, jobs, actors, tasks,
placement groups — polling ``/api/*`` with no build toolchain, plus the
Chrome-trace timeline download. Served by ``DashboardHead`` at ``/``.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray-tpu dashboard</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 1.5rem;
         color: #222; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin: 1.4rem 0 .4rem; }
  table { border-collapse: collapse; width: 100%; font-size: .85rem; }
  th, td { border: 1px solid #ddd; padding: .3rem .5rem;
           text-align: left; }
  th { background: #f4f4f4; }
  .pill { padding: .1rem .45rem; border-radius: .6rem;
          font-size: .75rem; }
  .ok { background: #d9f2d9; }
  .bad { background: #f6d3d3; }
  .muted { color: #777; }
  #summary span { margin-right: 1.2rem; }
  a.button { display: inline-block; padding: .25rem .6rem;
             border: 1px solid #888; border-radius: .3rem;
             text-decoration: none; color: #222; }
</style>
</head>
<body>
<h1>ray-tpu dashboard <span id="version" class="muted"></span></h1>
<div id="summary"></div>
<p><a class="button" href="/api/timeline" download="timeline.json">
  Download task timeline (Chrome trace)</a>
<a class="button" href="/timeline" download="perfetto_trace.json">
  Download flight-recorder trace (Perfetto)</a>
<a class="button" href="/api/v0/events">Flight-recorder events (JSON)</a></p>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Worker processes</h2><table id="procs"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Placement groups</h2><table id="pgs"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<script>
const esc = (s) => s.replace(/&/g, "&amp;").replace(/</g, "&lt;")
  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
const fmt = (v) => v === null || v === undefined ? "" :
  esc(typeof v === "object" ? JSON.stringify(v) : String(v));
function table(el, rows, cols, raw) {
  if (!rows || !rows.length) {
    el.innerHTML = "<tr><td class='muted'>none</td></tr>"; return;
  }
  cols = cols || Object.keys(rows[0]);
  raw = raw || [];
  let html = "<tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>";
  for (const r of rows) {
    html += "<tr>" + cols.map(c => {
      let v = raw.includes(c) ? (r[c] || "") : fmt(r[c]);
      if (c === "alive" || c === "state" || c === "status") {
        const good = v === "true" || v === "ALIVE" || v === "RUNNING"
          || v === "FINISHED" || v === "SUCCEEDED" || v === "CREATED";
        v = `<span class="pill ${good ? "ok" : "bad"}">${v}</span>`;
      }
      return `<td>${v}</td>`;
    }).join("") + "</tr>";
  }
  el.innerHTML = html;
}
async function j(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(url + ": " + r.status);
  return r.json();
}
async function refresh() {
  try {
    const [ver, status, nodes, jobs, actors, pgs, tasks, procs] =
      await Promise.all([
        j("/api/version"), j("/api/cluster_status"),
        j("/api/state/nodes"), j("/api/jobs"),
        j("/api/state/actors"), j("/api/state/placement_groups"),
        j("/api/state/tasks?limit=50"),
        j("/api/state/node_processes")]);
    document.getElementById("version").textContent =
      "v" + ver.version + " — " + ver.ray_tpu_session;
    const st = status.task_states || {};
    document.getElementById("summary").innerHTML =
      `<span><b>${(nodes.rows||[]).length}</b> nodes</span>` +
      `<span><b>${status.num_actors}</b> actors</span>` +
      `<span><b>${status.num_objects}</b> objects</span>` +
      `<span><b>${status.num_pending_tasks}</b> pending tasks</span>` +
      Object.entries(st).map(
        ([k, v]) => `<span class="muted">${k}: ${v}</span>`).join("");
    table(document.getElementById("nodes"), nodes.rows,
      ["node_id", "alive", "resources_total", "resources_available",
       "num_workers", "labels"]);
    // live per-process stats from each node's agent feed; the profile
    // link returns the worker's collapsed-stack flamegraph artifact
    const prows = (procs.rows || []).map(p => ({
      node: (p.node_id || "").slice(0, 12), kind: p.kind, pid: p.pid,
      "cpu %": p.cpu_percent,
      "rss MiB": Math.round((p.rss || 0) / 1048576),
      threads: p.num_threads,
      profile: p.worker_id ?
        `<a class="button" href="/api/nodes/${p.node_id}/profile` +
        `?worker=${p.worker_id}&duration=2">sample</a>` : ""}));
    table(document.getElementById("procs"), prows, null, ["profile"]);
    table(document.getElementById("jobs"), jobs.jobs || jobs);
    table(document.getElementById("actors"), actors.rows,
      ["actor_id", "state", "name", "namespace", "num_restarts",
       "node_id"]);
    table(document.getElementById("pgs"), pgs.rows);
    table(document.getElementById("tasks"),
      (tasks.rows || []).slice(-50).reverse());
  } catch (e) {
    document.getElementById("summary").innerHTML =
      `<span class="pill bad">refresh failed: ${e}</span>`;
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
