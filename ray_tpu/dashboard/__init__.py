"""Dashboard-lite: job submission + cluster-state REST API.

Reference: ``python/ray/dashboard/`` — the full aiohttp dashboard head
with per-module handlers. Here the surface is a stdlib ThreadingHTTPServer
in the head process serving JSON (a TPU pod head has no need for the
reference's React frontend or per-node agents; the state API already
aggregates cluster state at the controller).
"""

from ray_tpu.dashboard.job_manager import JobManager, JobStatus

__all__ = ["JobManager", "JobStatus"]
