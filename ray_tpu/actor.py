"""Actors: stateful remote workers.

Equivalent of the reference's ``python/ray/actor.py`` (``ActorClass`` :544,
``_remote`` :830, ``ActorHandle``, ``ActorMethod``). An actor occupies a
dedicated worker process for its lifetime; method calls are ordered
per-caller (the control plane preserves per-peer order); handles are
picklable and usable from any process.
"""

from __future__ import annotations

import hashlib
import inspect
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core.global_state import global_worker
from ray_tpu.core.ids import ActorID, TaskID
from ray_tpu.core.task_spec import FunctionDescriptor, TaskSpec
from ray_tpu.remote_function import (
    _prepare_env, make_scheduling_strategy, resources_from_opts)

_ACTOR_DEFAULT_OPTS = dict(
    num_cpus=1.0, num_tpus=0.0, resources=None, max_restarts=0,
    max_task_retries=0, max_concurrency=1, max_pending_calls=-1,
    name=None, namespace="", lifetime=None, scheduling_strategy=None,
    runtime_env=None, memory=None, placement_group=None,
    placement_group_bundle_index=-1,
)


def method(**opts):
    """Decorator for per-method options (reference: ray.method)."""
    def deco(fn):
        fn.__ray_tpu_method_opts__ = opts
        return fn
    return deco


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._opts = dict(_ACTOR_DEFAULT_OPTS)
        self._opts.update(options)
        self.__name__ = cls.__name__
        self._pickled: Optional[bytes] = None
        self._descriptor: Optional[FunctionDescriptor] = None
        self._exported_sessions = set()
        self._is_async = any(
            inspect.iscoroutinefunction(v) or inspect.isasyncgenfunction(v)
            for v in vars(cls).values() if callable(v))

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote().")

    def options(self, **overrides) -> "ActorClass":
        ac = ActorClass(self._cls, **{**self._opts, **overrides})
        ac._pickled = self._pickled
        ac._descriptor = self._descriptor
        ac._exported_sessions = self._exported_sessions
        return ac

    def _ensure_exported(self, w) -> FunctionDescriptor:
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._cls)
            h = hashlib.sha1(self._pickled).hexdigest()[:16]
            self._descriptor = FunctionDescriptor(
                module=getattr(self._cls, "__module__", "") or "",
                qualname=self._cls.__qualname__, function_hash=h)
        key = self._descriptor.key()
        if id(w) not in self._exported_sessions:
            w.export_function(key, self._pickled)
            self._exported_sessions.add(id(w))
        return self._descriptor

    def remote(self, *args, **kwargs) -> "ActorHandle":
        opts = self._opts
        from ray_tpu.remote_function import _client_route
        client = _client_route()
        if client is not None:
            if getattr(self, "_client_cls", None) is None:
                self._client_cls = client._wrap(
                    self._cls,
                    {k: v for k, v in opts.items() if v is not None})
            return self._client_cls.remote(*args, **kwargs)
        # default-resource actors release their scheduling CPU once alive
        hold = any(opts.get(k) not in (None, _ACTOR_DEFAULT_OPTS.get(k))
                   for k in ("num_cpus", "num_tpus", "resources", "memory"))
        w = global_worker()
        descriptor = self._ensure_exported(w)
        actor_id = ActorID.of(w.job_id)
        args_blob, arg_refs, _ = w.serialize_args(args, kwargs)
        max_concurrency = opts["max_concurrency"]
        if self._is_async and max_concurrency == 1:
            max_concurrency = 1000  # reference default for async actors
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(actor_id),
            job_id=w.job_id,
            function=descriptor,
            args_blob=args_blob,
            arg_refs=[(i, oid) for i, oid in arg_refs],
            num_returns=1,
            resources=resources_from_opts(opts),
            scheduling_strategy=make_scheduling_strategy(opts),
            is_actor_creation=True,
            hold_resources=hold,
            actor_id=actor_id,
            max_restarts=opts["max_restarts"],
            max_task_retries=opts["max_task_retries"],
            max_concurrency=max_concurrency,
            max_pending_calls=opts["max_pending_calls"],
            actor_name=opts.get("name") or "",
            namespace=opts.get("namespace") or "",
            is_async_actor=self._is_async,
            name=f"{self.__name__}.__init__",
            runtime_env=_prepare_env(w, opts.get("runtime_env")),
        )
        w.create_actor(spec)
        return ActorHandle(actor_id, self.__name__,
                           max_task_retries=opts["max_task_retries"])

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1,
                 backpressure: int = 0):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._backpressure = backpressure

    def options(self, **opts) -> "ActorMethod":
        m = ActorMethod(self._handle, self._name,
                        opts.get("num_returns", self._num_returns),
                        int(opts.get("generator_backpressure_num_objects")
                            or self._backpressure))
        return m

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._name, args, kwargs, self._num_returns,
            self._backpressure)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassMethodNode
        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "",
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries
        self._seq = 0

    @property
    def _id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_") and not name.startswith("__ray"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def _submit_method(self, name: str, args, kwargs, num_returns,
                       backpressure: int = 0):
        w = global_worker()
        args_blob, arg_refs, _ = w.serialize_args(args, kwargs)
        self._seq += 1
        from ray_tpu.core.task_spec import STREAMING_RETURNS
        streaming = num_returns == "streaming"
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(self._actor_id),
            job_id=w.job_id,
            function=FunctionDescriptor("", name, ""),
            args_blob=args_blob,
            arg_refs=[(i, oid) for i, oid in arg_refs],
            num_returns=STREAMING_RETURNS if streaming else num_returns,
            actor_id=self._actor_id,
            sequence_number=self._seq,
            max_retries=self._max_task_retries,
            name=f"{self._class_name}.{name}",
            backpressure=backpressure,
        )
        if streaming:
            return w.submit_streaming_task(spec)
        refs = w.submit_task(spec)
        return refs[0] if num_returns == 1 else refs

    def __ray_ready__(self):
        return self._submit_method("__ray_ready__", (), {}, 1)

    def __reduce__(self):
        return (_rebuild_handle,
                (self._actor_id.binary(), self._class_name,
                 self._max_task_retries))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


def _rebuild_handle(actor_id_b: bytes, class_name: str, max_task_retries: int):
    return ActorHandle(ActorID(actor_id_b), class_name, max_task_retries)
