"""Slice-granular gang scheduling: the SliceManager.

A TPU pod slice is the atomic multi-host unit everything multi-host
rides on: its host VMs share one ICI domain, come up together, and are
preempted together (maintenance events hit the slice, not a VM).
Nothing below this layer can acquire "4 hosts that can talk" — only a
slice can. The reference splits this between the GCS placement-group
manager (gang bundles) and the autoscaler's TPU pod handling
(``python/ray/_private/accelerators/tpu.py`` gang resources +
``gcp/node.py`` slice provisioning); here one controller-side manager
owns the whole lifecycle:

- **acquire**: :meth:`SliceManager.acquire_slice` asks the provider
  (``NodeProvider.create_slice`` — GCE/GKE/Fake) for a whole slice;
  the slice is ``REQUESTED`` until every host VM registers with the
  controller carrying the slice's id in its ``ray-tpu-slice-id``
  label, then ``UP`` (flight-recorder ``SLICE_UP``).
- **gang placement**: pending ``SLICE_PACK``/``SLICE_SPREAD``
  placement groups (``util/placement_group.py``) are whole-slice
  demand — :func:`plan_slice_scaling` converts them into acquire
  decisions; the bundle planner
  (``core/scheduler.py::_plan_slice_bundles``) then reserves all
  bundles across the slice's distinct hosts all-or-nothing.
- **preemption-aware drain**: provider ``maintenance_events`` (real
  upcoming-maintenance notices, or simulated ones from the chaos
  harness — ``ChaosConfig.maintenance``) flip the slice to
  ``DRAINING`` (``SLICE_DRAIN``): its hosts stop taking leases
  (scheduler draining flag), its placement groups are torn down and
  re-queued (``Controller._reschedule_pgs_on_nodes`` →
  ``RESCHEDULING`` → a fresh slice), and after the drain window (or
  ``drain_deadline_s``, so a stuck workload can never hang the
  release) the slice is deleted and its hosts declared dead
  (``SLICE_DOWN`` with the drain duration; in-flight actor calls
  surface typed ``ActorUnavailableError`` and restart on the new
  reservation).
- **scale-down as a unit**: an idle slice (no leases/actors on ANY
  host past ``idle_timeout_s``) drains atomically —
  ``drain_nodes_if_idle`` vetoes if one host got busy — and is
  released whole.

Fleet gauges (``core/metric_defs.py``): ``autoscaler_slices_up``,
``autoscaler_slice_hosts_pending``, ``autoscaler_slice_drain_seconds``.
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

from ray_tpu.autoscaler.node_provider import (
    NodeProvider, SliceCapacityError)
from ray_tpu.core.scheduler import SLICE_LABEL  # noqa: F401 (re-export)

logger = logging.getLogger(__name__)

# slice lifecycle (a deliberate miniature of the v2 instance machine:
# a slice is REQUESTED until whole, never partially UP)
REQUESTED = "REQUESTED"
UP = "UP"
DRAINING = "DRAINING"
RELEASED = "RELEASED"


def hosts_for_topology(topology: str, chips_per_host: int = 4) -> int:
    """Host-VM count of a TPU slice topology string (``"2x2"``,
    ``"4x4"``, ``"2x2x4"``): chips = the product of the axes, 4 chips
    per host VM (the v4/v5p host layout), minimum one host. Unknown
    strings raise ``ValueError`` — a topology typo must fail at config
    validation, not at provisioning time."""
    if not isinstance(topology, str):
        raise ValueError(
            f"slice topology must be a string like '2x2', got "
            f"{type(topology).__name__}")
    parts = topology.strip().lower().split("x")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"unknown slice topology {topology!r}: expected 'AxB' or "
            f"'AxBxC' (chip axes, e.g. '2x2', '4x4', '2x2x4')")
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        raise ValueError(
            f"unknown slice topology {topology!r}: axes must be "
            f"integers") from None
    if any(d <= 0 for d in dims):
        raise ValueError(
            f"unknown slice topology {topology!r}: axes must be "
            f"positive")
    chips = math.prod(dims)
    return max(1, chips // max(1, chips_per_host))


@dataclass
class SliceTypeConfig:
    """One acquirable slice flavor (the ``slices:`` section of the
    cluster YAML — see ``autoscaler/launcher.py``)."""
    name: str
    topology: str = "2x2"
    host_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1})
    min_slices: int = 0
    max_slices: int = 4

    @property
    def num_hosts(self) -> int:
        return hosts_for_topology(self.topology)


@dataclass(frozen=True)
class DrainNotice:
    """One drain notice, delivered to ``on_drain`` callbacks exactly
    once per slice drain (the DRAINING state guard makes a second
    notice for the same drain a no-op). ``deadline_s`` is the
    manager's ``drain_deadline_s`` — the longest a consumer can count
    on the slice's hosts staying up before the forced release."""
    slice_id: str
    reason: str
    hosts: int
    type: str
    deadline_s: float
    ts: float = field(default_factory=time.monotonic)


@dataclass
class SliceInfo:
    """Tracked lifecycle of one acquired slice."""
    slice_id: str
    type: str
    num_hosts: int
    state: str = REQUESTED
    created_at: float = field(default_factory=time.monotonic)
    hosts_joined: int = 0  # host VMs registered AND alive
    up_at: Optional[float] = None
    draining_since: Optional[float] = None
    drain_reason: str = ""
    released_at: Optional[float] = None


def _demand_feasible(t: SliceTypeConfig, demand: dict) -> bool:
    """Can ONE slice of this type ever host the gang? (host count and
    per-bundle shape only — the bundle planner does live capacity)."""
    if t.num_hosts < int(demand.get("hosts", 1)):
        return False
    for b in demand.get("bundles", ()):
        if any(t.host_resources.get(k, 0.0) < v for k, v in b.items()):
            return False
    return True


def plan_slice_scaling(slice_demand: List[dict],
                       slices: Iterable[SliceInfo],
                       slice_types: Dict[str, SliceTypeConfig],
                       idle_slice_ids: Iterable[str] = ()
                       ) -> Dict[str, Any]:
    """Pure decision function: (pending slice-spanning gangs, tracked
    slices) -> ``{"acquire": {type: n}, "release": [slice_id]}``.

    Each demand entry is ``{"hosts": h, "bundles": [res, ...]}``
    (``collect_demand_snapshot``'s ``slice_demand``). Matching is
    deliberately conservative: each live (REQUESTED/UP, non-draining)
    slice absorbs one pending gang — two gangs that could co-reside
    may transiently over-provision, and the idle scale-down reclaims
    the extra slice. Idle slices release only above the type's
    ``min_slices`` floor and only when no gang is pending."""
    live = [s for s in slices if s.state in (REQUESTED, UP)]
    free = {s.slice_id: s for s in live}
    counts: Dict[str, int] = {}
    for s in live:
        counts[s.type] = counts.get(s.type, 0) + 1

    acquire: Dict[str, int] = {}
    for d in slice_demand:
        # an existing slice big enough absorbs the gang (the bundle
        # planner will fit it for real)
        taken = None
        for sid, s in sorted(free.items()):
            t = slice_types.get(s.type)
            if t is not None and _demand_feasible(t, d) \
                    and s.num_hosts >= int(d.get("hosts", 1)):
                taken = sid
                break
        if taken is not None:
            del free[taken]
            continue
        for name in sorted(slice_types):
            t = slice_types[name]
            total = counts.get(name, 0) + acquire.get(name, 0)
            if total >= t.max_slices:
                continue
            if _demand_feasible(t, d):
                acquire[name] = acquire.get(name, 0) + 1
                break
        # infeasible demand stays pending (the scheduler keeps the
        # group queued; nothing to launch)

    # min_slices floor
    for name, t in slice_types.items():
        total = counts.get(name, 0) + acquire.get(name, 0)
        if total < t.min_slices:
            acquire[name] = acquire.get(name, 0) + \
                (t.min_slices - total)

    release: List[str] = []
    if not slice_demand:
        by_type: Dict[str, List[SliceInfo]] = {}
        for s in live:
            if s.state == UP:
                by_type.setdefault(s.type, []).append(s)
        idle = set(idle_slice_ids)
        for name, insts in by_type.items():
            t = slice_types.get(name)
            floor = t.min_slices if t else 0
            killable = [s for s in insts if s.slice_id in idle]
            for s in killable[:max(0, len(insts) - floor)]:
                release.append(s.slice_id)
    return {"acquire": acquire, "release": release}


class SliceManager:
    """Controller-side owner of the slice lifecycle (see module
    docstring). Drives any :class:`NodeProvider` with the slice API;
    composes with :class:`~ray_tpu.autoscaler.v2.AutoscalerV2`
    (``slice_manager=``) or runs standalone via :meth:`update` under
    an ``AutoscalerMonitor``."""

    def __init__(self, controller, provider: NodeProvider,
                 slice_types: List[SliceTypeConfig],
                 idle_timeout_s: float = 60.0,
                 drain_deadline_s: float = 30.0,
                 recorder=None):
        self.controller = controller
        self.provider = provider
        self.slice_types = {t.name: t for t in slice_types}
        self.idle_timeout_s = idle_timeout_s
        self.drain_deadline_s = drain_deadline_s
        self.slices: Dict[str, SliceInfo] = {}
        self._idle_since: Dict[str, float] = {}
        self._drain_callbacks: List[Any] = []
        self._recorder = recorder if recorder is not None \
            else getattr(controller, "recorder", None)
        self.adopt_existing()

    # ----------------------------------------------------- drain hook
    def register_on_drain(self, callback) -> Any:
        """Register ``callback(notice: DrainNotice)`` to run when a
        slice flips to DRAINING — fired exactly once per notice (the
        DRAINING/RELEASED guard in :meth:`drain_slice` dedupes), AFTER
        the slice's placement groups were re-queued and BEFORE the
        release, so an elastic trainer can snapshot from the still-live
        hosts.

        The hook is MULTI-SUBSCRIBER: every registered callback
        observes every notice (an arbiter and an ``ElasticTrainer``
        both see the same drain without stealing it from each other).
        Dispatch order is registration order (FIFO), and a callback
        unregistered while a dispatch is in flight — including by an
        earlier callback of the SAME dispatch — is skipped rather than
        fired against a subscriber that believes it already detached.
        Callbacks run synchronously on the draining thread; exceptions
        are logged and swallowed, and a callback that never consumes
        its notice cannot block the ``drain_deadline_s`` release path —
        release is driven by :meth:`_finish_drains`, not by callback
        completion. Returns the callback (decorator friendly)."""
        self._drain_callbacks.append(callback)
        return callback

    def unregister_on_drain(self, callback) -> None:
        try:
            self._drain_callbacks.remove(callback)
        except ValueError:
            pass

    def _dispatch_drain_notice(self, notice: "DrainNotice") -> int:
        """Fan one notice out to every live subscriber in registration
        order. The snapshot fixes the order; the membership check at
        call time honors unregister-during-dispatch (a subscriber
        removed by an earlier callback in this same dispatch must not
        fire). Returns the number of callbacks actually invoked."""
        fired = 0
        for cb in list(self._drain_callbacks):
            if cb not in self._drain_callbacks:
                continue
            fired += 1
            try:
                cb(notice)
            except Exception:
                logger.exception("on_drain callback failed for %s",
                                 notice.slice_id)
        return fired

    def adopt_existing(self) -> None:
        """Adopt slices the provider already tracks but this manager
        didn't acquire — e.g. the ``count:`` slices ``ray-tpu up``
        created before the head-started monitor came up. Without
        adoption the manager would double-acquire for the first gang
        an existing slice could host. Adopted slices start REQUESTED
        and flip UP through the normal :meth:`_sync` join path. Called
        at construction and on every :meth:`update` pass (cheap), so
        slices created by a concurrent launcher are picked up too."""
        reload_state = getattr(self.provider, "reload_state", None)
        if reload_state is not None:
            try:
                reload_state()
            except Exception:
                logger.exception("provider reload_state failed")
        try:
            existing = self.provider.non_terminated_nodes()
        except Exception:
            return
        for sid in existing:
            if sid in self.slices:
                continue
            try:
                tname = self.provider.node_type(sid)
            except Exception:
                continue
            t = self.slice_types.get(tname)
            if t is None:
                continue
            self.slices[sid] = SliceInfo(
                slice_id=sid, type=tname, num_hosts=t.num_hosts)
            logger.info("slices: adopted existing %s (%s, %d hosts)",
                        sid, tname, t.num_hosts)

    # -------------------------------------------------------- plumbing
    def _record(self, ev: str, **data) -> None:
        r = self._recorder
        if r is None:
            return
        try:
            r.record(ev, **data)
        except Exception:
            pass

    def _call_on_loop(self, fn):
        call = getattr(self.controller, "call_on_loop", None)
        return call(fn) if call is not None else fn()

    def _update_gauges(self) -> None:
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            m = runtime_metrics()
            m.slices_up.set(sum(
                1 for s in self.slices.values() if s.state == UP))
            m.slice_hosts_pending.set(sum(
                max(0, s.num_hosts - s.hosts_joined)
                for s in self.slices.values()
                if s.state == REQUESTED))
        except Exception:
            pass

    # ---------------------------------------------------------- acquire
    def acquire_slice(self, type_name: str) -> Optional[str]:
        """Request one whole slice of the named type; returns its id,
        or None when the provider is out of capacity (demand stays
        pending and a later pass retries)."""
        t = self.slice_types[type_name]
        try:
            sid = self.provider.create_slice(
                t.name, t.topology, dict(t.host_resources))
        except SliceCapacityError as e:
            logger.warning("slice acquire deferred (%s): %s",
                           type_name, e)
            return None
        self.slices[sid] = SliceInfo(
            slice_id=sid, type=type_name, num_hosts=t.num_hosts)
        logger.info("slices: requested %s (%s, %d hosts)", sid,
                    t.topology, t.num_hosts)
        return sid

    def wait_until_up(self, slice_id: str,
                      timeout_s: float = 60.0) -> bool:
        """Block (polling) until every host VM of the slice registered
        — test/launcher convenience; the reconcile loop never blocks
        here."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            snap = self._snapshot()
            self._sync(snap)
            info = self.slices.get(slice_id)
            if info is not None and info.state == UP:
                return True
            if info is None or info.state in (DRAINING, RELEASED):
                return False
            time.sleep(0.2)
        return False

    # ------------------------------------------------------------ drain
    def drain_slice(self, slice_id: str, reason: str) -> None:
        """Maintenance notice handling: stop new leases on every host,
        tear down + re-queue the slice's placement groups, and start
        the drain clock. The slice releases when its hosts go quiet or
        at ``drain_deadline_s`` — whichever comes first, so a wedged
        workload cannot hang the release."""
        info = self.slices.get(slice_id)
        if info is None or info.state in (DRAINING, RELEASED):
            return
        info.state = DRAINING
        info.draining_since = time.monotonic()
        info.drain_reason = reason
        self._record("SLICE_DRAIN", slice=slice_id, reason=reason,
                     hosts=info.num_hosts, type=info.type)
        logger.warning("slices: draining %s (%s)", slice_id, reason)
        host_bs = self.provider.internal_ids(slice_id)

        def _on_loop():
            from ray_tpu.core.ids import NodeID
            sched = getattr(self.controller, "scheduler", None)
            if sched is not None:
                for nb in host_bs:
                    sched.set_draining(NodeID(nb), True)
            resched = getattr(self.controller,
                              "_reschedule_pgs_on_nodes", None)
            moved = resched(set(host_bs)) if resched else 0
            kick = getattr(self.controller, "_maybe_schedule", None)
            if moved and kick is not None:
                kick()
            return moved

        try:
            moved = self._call_on_loop(_on_loop)
            if moved:
                logger.info("slices: re-queued %d placement group(s) "
                            "off %s", moved, slice_id)
        except Exception:
            logger.exception("slice drain hook failed for %s", slice_id)
        notice = DrainNotice(
            slice_id=slice_id, reason=reason, hosts=info.num_hosts,
            type=info.type, deadline_s=self.drain_deadline_s)
        self._dispatch_drain_notice(notice)
        self._update_gauges()

    def _release(self, slice_id: str) -> None:
        info = self.slices.get(slice_id)
        if info is None or info.state == RELEASED:
            return
        host_bs = self.provider.internal_ids(slice_id)
        try:
            self.provider.delete_slice(slice_id)
        except Exception:
            logger.exception("delete_slice failed for %s", slice_id)
        now = time.monotonic()
        drain_s = now - (info.draining_since or now)
        info.state = RELEASED
        info.released_at = now
        self._idle_since.pop(slice_id, None)

        # proactive death notice: the hosts are gone NOW — declaring
        # them dead immediately (instead of waiting out the heartbeat
        # threshold) lets stranded actors restart onto the group's
        # fresh reservation right away
        def _notify():
            nodes = getattr(self.controller, "nodes", None)
            dead = getattr(self.controller, "_on_node_dead", None)
            if nodes is None or dead is None:
                return
            for nb in host_bs:
                node = nodes.get(nb)
                if node is not None and node.alive:
                    dead(node)

        try:
            self._call_on_loop(_notify)
        except Exception:
            pass
        self._record("SLICE_DOWN", slice=slice_id,
                     reason=info.drain_reason or "released",
                     dur_s=round(drain_s, 6), hosts=info.num_hosts)
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            runtime_metrics().slice_drain_seconds.observe(drain_s)
        except Exception:
            pass
        logger.info("slices: released %s after %.2fs drain (%s)",
                    slice_id, drain_s, info.drain_reason or "idle")
        self._update_gauges()

    # -------------------------------------------------------- reconcile
    def _snapshot(self) -> dict:
        from ray_tpu.autoscaler.autoscaler import collect_demand_snapshot
        return self._call_on_loop(
            lambda: collect_demand_snapshot(self.controller))

    def _sync(self, snap: dict) -> None:
        """Observed state -> lifecycle transitions."""
        alive = snap.get("alive_nodes", set())
        for sid, info in list(self.slices.items()):
            if info.state == REQUESTED:
                ids = self.provider.internal_ids(sid)
                info.hosts_joined = sum(1 for i in ids if i in alive)
                if len(ids) >= info.num_hosts and \
                        all(i in alive for i in ids):
                    info.state = UP
                    info.up_at = time.monotonic()
                    self._record("SLICE_UP", slice=sid,
                                 hosts=info.num_hosts, type=info.type)
                    logger.info("slices: %s UP (%d hosts joined)",
                                sid, info.num_hosts)
            elif info.state == UP:
                ids = self.provider.internal_ids(sid)
                if ids and any(i not in alive for i in ids):
                    # a host died without notice (hard preemption):
                    # the slice is broken as a unit — drain + release
                    self.drain_slice(sid, "host-death")

    def poll_maintenance(self) -> List[dict]:
        """Consume the provider's drain notices (each reported once)."""
        try:
            events = self.provider.maintenance_events()
        except Exception:
            logger.exception("maintenance_events failed")
            return []
        for ev in events:
            sid = ev.get("slice_id")
            if sid in self.slices and \
                    self.slices[sid].state in (REQUESTED, UP):
                self.drain_slice(sid, ev.get("kind", "maintenance"))
        return events

    def _finish_drains(self, snap: dict) -> List[str]:
        busy_nodes = snap.get("busy_nodes", set())
        released = []
        now = time.monotonic()
        for sid, info in list(self.slices.items()):
            if info.state != DRAINING:
                continue
            ids = self.provider.internal_ids(sid)
            busy = any(i in busy_nodes for i in ids)
            deadline_hit = info.draining_since is not None and \
                now - info.draining_since >= self.drain_deadline_s
            if not busy or deadline_hit:
                self._release(sid)
                released.append(sid)
        return released

    def update(self, snap: Optional[dict] = None) -> Dict[str, Any]:
        """One reconcile pass: sync joins, consume maintenance, finish
        drains, then scale slice inventory to pending gang demand (up)
        and idleness (down, whole slices only)."""
        if snap is None:
            snap = self._snapshot()
        self.adopt_existing()
        self._sync(snap)
        self.poll_maintenance()
        released = self._finish_drains(snap)

        # idle tracking: a slice is idle only when EVERY host is quiet
        now = time.monotonic()
        slice_demand = snap.get("slice_demand", [])
        busy_nodes = snap.get("busy_nodes", set())
        idle = []
        for sid, info in self.slices.items():
            if info.state != UP or slice_demand:
                self._idle_since.pop(sid, None)
                continue
            ids = self.provider.internal_ids(sid)
            if any(i in busy_nodes for i in ids):
                self._idle_since.pop(sid, None)
                continue
            since = self._idle_since.setdefault(sid, now)
            if now - since >= self.idle_timeout_s:
                idle.append(sid)

        plan = plan_slice_scaling(
            slice_demand, self.slices.values(), self.slice_types, idle)
        acquired: List[str] = []
        for name, n in plan["acquire"].items():
            for _ in range(n):
                sid = self.acquire_slice(name)
                if sid:
                    acquired.append(sid)
        for sid in plan["release"]:
            ids = self.provider.internal_ids(sid)

            def _gang_drain(ids=ids):
                from ray_tpu.autoscaler.autoscaler import \
                    drain_nodes_if_idle
                return drain_nodes_if_idle(self.controller, list(ids))

            # atomic gang drain: one host getting busy between the
            # idle check and this call vetoes the whole slice
            try:
                ok = self._call_on_loop(_gang_drain) if ids else True
            except Exception:
                ok = False
            if not ok:
                self._idle_since.pop(sid, None)
                continue
            self.drain_slice(sid, "idle")
            self._release(sid)
            released.append(sid)
        self._update_gauges()
        return {"acquired": acquired, "released": released,
                "slices": {sid: s.state
                           for sid, s in self.slices.items()}}

    # ------------------------------------------------------------ views
    def stats(self) -> Dict[str, Any]:
        return {
            "slices_up": sum(1 for s in self.slices.values()
                             if s.state == UP),
            "slices_draining": sum(1 for s in self.slices.values()
                                   if s.state == DRAINING),
            "slices": {sid: {"state": s.state, "type": s.type,
                             "hosts": s.num_hosts}
                       for sid, s in self.slices.items()},
        }

    def shutdown(self) -> None:
        """Release every live slice (test teardown)."""
        for sid, info in list(self.slices.items()):
            if info.state in (REQUESTED, UP, DRAINING):
                self._release(sid)
