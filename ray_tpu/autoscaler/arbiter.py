"""Cluster-level slice arbitration between a serve fleet and an
elastic training job.

The :class:`SliceArbiter` is a priority/fair-share policy loop that
runs on the head next to the :class:`~ray_tpu.autoscaler.slices.
SliceManager` (under the same ``AutoscalerMonitor`` — construct with
``drive_manager=True`` and hand the arbiter to the monitor, and each
tick reconciles slices first, then arbitrates). It reads fleet gauges
from the metrics plane — engine queue depth, TTFT p99, decode
occupancy vs training tokens/s — and moves whole slices between the
two workloads:

- **Sustained serve pressure** (queue depth or p99 TTFT above the
  policy's high-water marks for ``sustain_s``) → the arbiter drains
  the LOWEST-priority training slice (``drain_slice(sid,
  "arbiter-preempt")``). The ``ElasticTrainer`` observes the same
  multi-subscriber drain notice and re-lowers onto the survivors
  (≤ 1 step lost); the freed hosts serve the spike.
- **Pressure ebbs** past the hysteresis low-water marks for ``ebb_s``
  → the arbiter re-acquires a slice of the same type, hands the claim
  back to the training job, and fires its ``on_return`` subscribers so
  the trainer can :meth:`~ray_tpu.parallel.elastic.ElasticTrainer.
  regrow` the plan.

Ownership is explicit: workloads (the job layer, a bench, a test)
``claim()`` their slices with an owner name, a kind (``train`` /
``serve``) and an integer priority — higher wins, ties borrow the most
recently claimed slice first. The arbiter never preempts serve claims
and never drops the training job below ``min_train_slices``.

Every decision is observable: ``ARBITER_PREEMPT`` / ``ARBITER_RETURN``
flight events carry ``dur_s`` (the sustained-pressure window and the
whole borrow window respectively — both render as Perfetto duration
slices on ``/timeline``) and the
``autoscaler_arbiter_preemptions_total{reason}`` /
``autoscaler_arbiter_returns_total{reason}`` counters feed the
metrics plane. :meth:`status` returns the live per-slice ownership
rows the dashboard's ``/api/v0/arbiter`` route and ``ray-tpu jobs``
print.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.slices import RELEASED, UP

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ArbiterPolicy:
    """Knobs of the pressure detector and the fair-share rules.

    Pressure is declared when ANY high-water mark is crossed and held
    for ``sustain_s``; calm requires EVERY low-water mark for
    ``ebb_s`` (hysteresis — the gap between the two marks is the
    flap-damping band)."""

    #: per-replica engine queue depth above which serve is under
    #: pressure (the engine admits but requests wait for slots)
    queue_high: float = 4.0
    #: fleet p99 TTFT (ms) above which serve is under pressure
    ttft_p99_high_ms: float = 2000.0
    #: queue depth at/below which pressure has ebbed
    queue_low: float = 1.0
    #: p99 TTFT (ms) at/below which pressure has ebbed
    ttft_p99_low_ms: float = 1000.0
    #: pressure must hold this long before a preemption fires
    sustain_s: float = 2.0
    #: calm must hold this long before a borrowed slice returns
    ebb_s: float = 4.0
    #: training never drops below this many UP/REQUESTED slices
    min_train_slices: int = 0
    #: at most this many slices borrowed from training at once
    max_borrowed: int = 1
    #: metrics-plane window fed to ``fleet_summary``
    window_s: float = 30.0


@dataclasses.dataclass
class SliceClaim:
    """One workload's ownership of one slice."""

    slice_id: str
    owner: str
    kind: str              # "train" | "serve"
    priority: int          # higher = more important
    claimed_at: float = 0.0


@dataclasses.dataclass
class _Borrow:
    """A train slice the arbiter took for serve, awaiting return."""

    claim: SliceClaim
    slice_type: str
    preempted_at: float
    reason: str


class SliceArbiter:
    """See module docstring. ``update()`` is the whole contract — an
    ``AutoscalerMonitor`` drives it like any autoscaler."""

    def __init__(self, slice_manager,
                 policy: Optional[ArbiterPolicy] = None,
                 gauges_fn: Optional[Callable[[], Dict]] = None,
                 recorder=None,
                 drive_manager: bool = False,
                 now_fn: Callable[[], float] = time.monotonic):
        self.manager = slice_manager
        self.policy = policy or ArbiterPolicy()
        self._gauges_fn = gauges_fn
        self._recorder = recorder if recorder is not None \
            else getattr(slice_manager, "_recorder", None)
        self._drive_manager = drive_manager
        self._now = now_fn
        self.claims: Dict[str, SliceClaim] = {}
        self.borrowed: List[_Borrow] = []
        self._pressure_since: Optional[float] = None
        self._pressure_reason: str = ""
        self._calm_since: Optional[float] = None
        self._on_return: List[Callable[[Dict], None]] = []
        self.preemptions = 0
        self.returns = 0
        self._last_gauges: Dict[str, Any] = {}

    # ------------------------------------------------------ ownership
    def claim(self, slice_id: str, owner: str, kind: str,
              priority: int = 0) -> SliceClaim:
        """Record that ``owner`` runs on ``slice_id``. ``kind`` is
        ``"train"`` (preemptible by policy) or ``"serve"`` (never
        preempted)."""
        if kind not in ("train", "serve"):
            raise ValueError(f"unknown claim kind {kind!r}")
        c = SliceClaim(slice_id=slice_id, owner=owner, kind=kind,
                       priority=priority, claimed_at=self._now())
        self.claims[slice_id] = c
        return c

    def release_claim(self, slice_id: str) -> None:
        self.claims.pop(slice_id, None)

    def register_on_return(self, callback) -> Any:
        """``callback(info)`` fires after a borrowed slice is handed
        back to training; ``info`` carries ``slice_id`` (the NEW
        slice), ``owner``, ``type`` and ``borrowed_s``. Returns the
        callback (decorator friendly)."""
        self._on_return.append(callback)
        return callback

    def unregister_on_return(self, callback) -> None:
        try:
            self._on_return.remove(callback)
        except ValueError:
            pass

    # -------------------------------------------------------- gauges
    def _gauges(self) -> Dict[str, Any]:
        """Serve-pressure signals, normalized. Sources, in order: an
        injected ``gauges_fn`` (tests, the colocate bench), the
        controller's in-process metrics plane (``fleet_summary`` rows),
        or — when the arbiter runs in a driver/monitor process with no
        direct controller reference — the live metrics plane over the
        state API (``fleet_metrics`` query), so an
        ``AutoscalerMonitor``-driven arbiter needs no injection at
        all."""
        if self._gauges_fn is not None:
            raw = self._gauges_fn() or {}
        else:
            plane = getattr(getattr(self.manager, "controller", None),
                            "metrics_plane", None)
            if plane is not None:
                raw = plane.fleet_summary(
                    window_s=self.policy.window_s)
            else:
                try:
                    from ray_tpu.util.state import fleet_metrics
                    raw = fleet_metrics(
                        window_s=self.policy.window_s) or {}
                except Exception:
                    return {}
        if "rows" in raw:        # fleet_summary payload → normalize
            rows = raw.get("rows") or []
            depths = [r["queue_depth"] for r in rows
                      if r.get("queue_depth") is not None]
            p99s = [r["ttft_p99_ms"] for r in rows
                    if r.get("ttft_p99_ms") is not None]
            fleet = raw.get("fleet") or {}
            return {
                "queue_depth": max(depths) if depths else 0.0,
                "ttft_p99_ms": max(p99s) if p99s else 0.0,
                "serve_tokens_per_s": fleet.get("tokens_per_s", 0.0),
                "train_tokens_per_s": fleet.get(
                    "train_tokens_per_s", 0.0),
            }
        return raw

    def _classify(self, g: Dict[str, Any]):
        """(pressure?, calm?, reason) from one gauge sample."""
        q = float(g.get("queue_depth") or 0.0)
        p99 = float(g.get("ttft_p99_ms") or 0.0)
        pol = self.policy
        if q >= pol.queue_high:
            return True, False, "queue-depth"
        if p99 >= pol.ttft_p99_high_ms:
            return True, False, "ttft-p99"
        calm = q <= pol.queue_low and p99 <= pol.ttft_p99_low_ms
        return False, calm, ""

    # -------------------------------------------------------- policy
    def _train_claims_up(self) -> List[SliceClaim]:
        out = []
        for sid, c in self.claims.items():
            if c.kind != "train":
                continue
            info = self.manager.slices.get(sid)
            if info is not None and info.state == UP:
                out.append(c)
        return out

    def _pick_victim(self) -> Optional[SliceClaim]:
        """Lowest priority first; ties borrow the most recently
        claimed slice (the training job keeps its oldest, warmest
        capacity)."""
        candidates = self._train_claims_up()
        if len(candidates) <= self.policy.min_train_slices:
            return None
        candidates.sort(key=lambda c: (c.priority, -c.claimed_at))
        return candidates[0]

    def _record(self, ev: str, **data) -> None:
        r = self._recorder
        if r is None:
            return
        try:
            r.record(ev, **data)
        except Exception:
            pass

    def _count(self, counter: str, **tags) -> None:
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            getattr(runtime_metrics(), counter).inc(tags=tags)
        except Exception:
            pass

    def _preempt(self, victim: SliceClaim, reason: str,
                 sustained_s: float) -> None:
        info = self.manager.slices.get(victim.slice_id)
        slice_type = info.type if info is not None else ""
        now = self._now()
        self.manager.drain_slice(victim.slice_id,
                                 "arbiter-preempt")
        self.claims.pop(victim.slice_id, None)
        self.borrowed.append(_Borrow(
            claim=victim, slice_type=slice_type,
            preempted_at=now, reason=reason))
        self.preemptions += 1
        self._record("ARBITER_PREEMPT", slice=victim.slice_id,
                     reason=reason, owner=victim.owner,
                     priority=victim.priority,
                     dur_s=round(sustained_s, 6))
        self._count("arbiter_preemptions", reason=reason)
        logger.warning(
            "arbiter: preempting train slice %s of %s (prio %d) — "
            "%s sustained %.1fs", victim.slice_id, victim.owner,
            victim.priority, reason, sustained_s)

    def _return_one(self) -> bool:
        """Hand ONE borrowed slice back to training; False on
        provider stockout (retried next tick, the borrow stays)."""
        borrow = self.borrowed[0]
        sid = self.manager.acquire_slice(borrow.slice_type)
        if sid is None:
            return False
        self.borrowed.pop(0)
        c = borrow.claim
        self.claim(sid, c.owner, "train", c.priority)
        borrowed_s = self._now() - borrow.preempted_at
        self.returns += 1
        self._record("ARBITER_RETURN", slice=sid, owner=c.owner,
                     reason="pressure-ebbed",
                     dur_s=round(borrowed_s, 6))
        self._count("arbiter_returns", reason="pressure-ebbed")
        logger.info("arbiter: returned slice %s to %s after %.1fs "
                    "borrow", sid, c.owner, borrowed_s)
        info = {"slice_id": sid, "owner": c.owner,
                "type": borrow.slice_type,
                "borrowed_s": round(borrowed_s, 6)}
        for cb in list(self._on_return):
            if cb not in self._on_return:
                continue
            try:
                cb(info)
            except Exception:
                logger.exception("on_return callback failed for %s",
                                 sid)
        return True

    # --------------------------------------------------------- update
    def update(self) -> Dict[str, Any]:
        """One arbitration tick (monitor-driven)."""
        if self._drive_manager:
            try:
                self.manager.update()
            except Exception:
                logger.exception("arbiter: manager reconcile failed")
        # drop claims whose slice is gone (released under us)
        for sid in list(self.claims):
            info = self.manager.slices.get(sid)
            if info is not None and info.state == RELEASED:
                self.claims.pop(sid, None)
        g = self._gauges()
        self._last_gauges = dict(g)
        pressure, calm, reason = self._classify(g)
        now = self._now()
        actions: List[str] = []

        if pressure:
            self._calm_since = None
            if self._pressure_since is None:
                self._pressure_since = now
                self._pressure_reason = reason
            sustained = now - self._pressure_since
            if sustained >= self.policy.sustain_s and \
                    len(self.borrowed) < self.policy.max_borrowed:
                victim = self._pick_victim()
                if victim is not None:
                    self._preempt(victim, self._pressure_reason
                                  or reason, sustained)
                    actions.append(f"preempt:{victim.slice_id}")
                    # a further preemption needs a FRESH sustained
                    # window — one slice per pressure episode
                    self._pressure_since = now
        else:
            self._pressure_since = None
            if calm:
                if self._calm_since is None:
                    self._calm_since = now
                if self.borrowed and \
                        now - self._calm_since >= self.policy.ebb_s:
                    if self._return_one():
                        actions.append("return")
            else:
                self._calm_since = None
        return {"pressure": pressure, "calm": calm,
                "reason": reason or self._pressure_reason,
                "borrowed": len(self.borrowed), "actions": actions}

    # --------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        """Live ownership rows for the dashboard / ``ray-tpu jobs``:
        who owns which slices and why."""
        rows = []
        for sid, c in sorted(self.claims.items()):
            info = self.manager.slices.get(sid)
            rows.append({
                "slice_id": sid, "owner": c.owner, "kind": c.kind,
                "priority": c.priority,
                "state": info.state if info is not None else "?",
                "why": "claimed",
            })
        for b in self.borrowed:
            info = self.manager.slices.get(b.claim.slice_id)
            state = info.state if info is not None else "RELEASED"
            rows.append({
                "slice_id": b.claim.slice_id, "owner": b.claim.owner,
                "kind": "train", "priority": b.claim.priority,
                "state": state,
                "why": f"borrowed-by-serve ({b.reason})",
            })
        return {
            "rows": rows,
            "pressure": self._pressure_since is not None,
            "pressure_reason": self._pressure_reason,
            "borrowed": len(self.borrowed),
            "preemptions": self.preemptions,
            "returns": self.returns,
            "gauges": dict(self._last_gauges),
            "policy": dataclasses.asdict(self.policy),
        }

    def stats(self) -> Dict[str, Any]:
        return {"preemptions": self.preemptions,
                "returns": self.returns,
                "borrowed": len(self.borrowed),
                "claims": len(self.claims)}
