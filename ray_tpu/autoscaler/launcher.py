"""Cluster launcher: ``ray-tpu up / down / attach`` from a YAML config.

Reference: ``python/ray/autoscaler/_private/commands.py`` (``ray up`` —
validate config, create or update head node, bootstrap it over SSH,
start the autoscaler there) with the schema contract of
``python/ray/autoscaler/ray-schema.json``. TPU-native differences: the
provisioning unit is a TPU pod slice (see gce.py), the head is itself a
TPU VM (or an existing address), and bootstrap commands run on every
host VM of a slice via the command runner (reference:
``gcp/tpu_command_runner.py`` fans one runner out per networkEndpoint).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

import yaml

from ray_tpu.autoscaler.autoscaler import NodeTypeConfig
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


# --------------------------------------------------------------- schema
class ConfigError(ValueError):
    """Invalid cluster YAML, with the offending path in the message."""


_PROVIDER_REQUIRED = {"gce_tpu": ("project", "zone")}


def validate_cluster_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + normalize a cluster config dict (reference:
    ray-schema.json, scoped to the fields this launcher consumes).
    Returns the config with defaults filled in."""
    if not isinstance(cfg, dict):
        raise ConfigError("cluster config must be a mapping")

    def need(d: dict, key: str, typ, path: str):
        if key not in d:
            raise ConfigError(f"missing required field '{path}{key}'")
        if not isinstance(d[key], typ):
            raise ConfigError(
                f"'{path}{key}' must be {typ.__name__}, "
                f"got {type(d[key]).__name__}")
        return d[key]

    need(cfg, "cluster_name", str, "")
    provider = need(cfg, "provider", dict, "")
    ptype = need(provider, "type", str, "provider.")
    for field in _PROVIDER_REQUIRED.get(ptype, ()):
        need(provider, field, str, "provider.")
    types = need(cfg, "available_node_types", dict, "")
    if not types:
        raise ConfigError("'available_node_types' must not be empty")
    for name, t in types.items():
        if not isinstance(t, dict):
            raise ConfigError(
                f"'available_node_types.{name}' must be a mapping")
        path = f"available_node_types.{name}."
        res = need(t, "resources", dict, path)
        for k, v in res.items():
            if not isinstance(v, (int, float)) or v < 0:
                raise ConfigError(
                    f"'{path}resources.{k}' must be a non-negative "
                    f"number")
        t.setdefault("min_workers", 0)
        t.setdefault("max_workers", cfg.get("max_workers", 8))
        for bound in ("min_workers", "max_workers"):
            if not isinstance(t[bound], int) or t[bound] < 0:
                raise ConfigError(
                    f"'{path}{bound}' must be a non-negative integer")
        if t["min_workers"] > t["max_workers"]:
            raise ConfigError(
                f"'{path}min_workers' ({t['min_workers']}) exceeds "
                f"max_workers ({t['max_workers']})")
        t.setdefault("node_config", {})
        if not isinstance(t["node_config"], dict):
            raise ConfigError(f"'{path}node_config' must be a mapping")
    head_type = need(cfg, "head_node_type", str, "")
    if head_type not in types:
        raise ConfigError(
            f"'head_node_type' {head_type!r} is not one of "
            f"available_node_types {sorted(types)}")
    # ---- slices: the gang units `ray-tpu up` brings up whole and the
    # SliceManager scales (autoscaler/slices.py)
    slices = cfg.setdefault("slices", {})
    if not isinstance(slices, dict):
        raise ConfigError("'slices' must be a mapping")
    from ray_tpu.autoscaler.slices import hosts_for_topology
    for name, s in slices.items():
        if not isinstance(s, dict):
            raise ConfigError(f"'slices.{name}' must be a mapping")
        path = f"slices.{name}."
        topo = need(s, "topology", str, path)
        try:
            n_hosts = hosts_for_topology(topo)
        except ValueError as e:
            raise ConfigError(f"'{path}topology': {e}") from None
        s.setdefault("count", 1)
        s.setdefault("min_slices", 0)
        s.setdefault("max_slices", max(int(s.get("count") or 0), 4))
        for bound in ("count", "min_slices", "max_slices"):
            if not isinstance(s[bound], int) or s[bound] < 0:
                raise ConfigError(
                    f"'{path}{bound}' must be a non-negative integer")
        if s["count"] > s["max_slices"]:
            raise ConfigError(
                f"'{path}count' ({s['count']}) exceeds max_slices "
                f"({s['max_slices']})")
        res = s.setdefault("host_resources", {"CPU": 1})
        if not isinstance(res, dict):
            raise ConfigError(f"'{path}host_resources' must be a mapping")
        for k, v in res.items():
            if not isinstance(v, (int, float)) or v < 0:
                raise ConfigError(
                    f"'{path}host_resources.{k}' must be a "
                    f"non-negative number")
        s.setdefault("node_config", {})
        if not isinstance(s["node_config"], dict):
            raise ConfigError(f"'{path}node_config' must be a mapping")
        placement = s.get("placement")
        if placement is not None:
            if not isinstance(placement, dict):
                raise ConfigError(f"'{path}placement' must be a mapping")
            strat = placement.setdefault("strategy", "SLICE_SPREAD")
            if strat not in ("SLICE_PACK", "SLICE_SPREAD"):
                raise ConfigError(
                    f"'{path}placement.strategy' must be SLICE_PACK "
                    f"or SLICE_SPREAD, got {strat!r}")
            bundles = placement.get("bundles")
            if not isinstance(bundles, list) or not bundles or \
                    not all(isinstance(b, dict) for b in bundles):
                raise ConfigError(
                    f"'{path}placement.bundles' must be a non-empty "
                    f"list of resource mappings")
            if strat == "SLICE_SPREAD" and len(bundles) > n_hosts:
                raise ConfigError(
                    f"'{path}placement.bundles': {len(bundles)} "
                    f"bundles exceed the {n_hosts} host VM(s) of "
                    f"topology {topo!r} (SLICE_SPREAD needs one "
                    f"distinct host per bundle)")
    # ---- arbiter: train+serve slice arbitration policy knobs
    # (autoscaler/arbiter.py) the head monitor drives next to the
    # SliceManager
    arbiter = cfg.get("arbiter")
    if arbiter is not None:
        if not isinstance(arbiter, dict):
            raise ConfigError("'arbiter' must be a mapping")
        import dataclasses as _dc

        from ray_tpu.autoscaler.arbiter import ArbiterPolicy
        known = {f.name for f in _dc.fields(ArbiterPolicy)}
        for k, v in arbiter.items():
            if k not in known:
                raise ConfigError(
                    f"'arbiter.{k}' is not a policy knob "
                    f"(one of {sorted(known)})")
            if not isinstance(v, (int, float)) or v < 0:
                raise ConfigError(
                    f"'arbiter.{k}' must be a non-negative number")
    cfg.setdefault("max_workers", 8)
    cfg.setdefault("setup_commands", [])
    cfg.setdefault("head_start_commands", [])
    cfg.setdefault("worker_start_commands", [])
    for key in ("setup_commands", "head_start_commands",
                "worker_start_commands"):
        if not isinstance(cfg[key], list) or \
                not all(isinstance(x, str) for x in cfg[key]):
            raise ConfigError(f"'{key}' must be a list of strings")
    auth = cfg.setdefault("auth", {})
    if not isinstance(auth, dict):
        raise ConfigError("'auth' must be a mapping")
    auth.setdefault("ssh_user", "ray")
    return cfg


def load_cluster_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        cfg = yaml.safe_load(f)
    return validate_cluster_config(cfg)


# -------------------------------------------------------- command runner
class CommandRunner:
    """Runs bootstrap commands on a cluster host (reference:
    command_runner.py CommandRunnerInterface)."""

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        raise NotImplementedError


class SSHCommandRunner(CommandRunner):
    def __init__(self, ip: str, user: str,
                 ssh_key: Optional[str] = None):
        self.ip = ip
        self.user = user
        self.ssh_key = ssh_key

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        ssh = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "ConnectTimeout=20"]
        if self.ssh_key:
            ssh += ["-i", self.ssh_key]
        ssh += [f"{self.user}@{self.ip}", cmd]
        logger.info("[%s] %s", self.ip, cmd)
        proc = subprocess.run(ssh, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"command failed on {self.ip} (rc={proc.returncode}): "
                f"{cmd}\n{proc.stderr[-2000:]}")
        return proc.stdout


# --------------------------------------------------------------- launcher
def _make_provider(cfg: Dict[str, Any],
                   api=None) -> NodeProvider:
    provider_cfg = dict(cfg["provider"])
    ptype = provider_cfg["type"]
    if ptype == "gce_tpu":
        from ray_tpu.autoscaler.gce import (
            GCETPUNodeProvider, state_resolver)
        from ray_tpu.autoscaler.slices import hosts_for_topology
        provider_cfg["cluster_name"] = cfg["cluster_name"]
        provider_cfg["node_configs"] = {
            name: t.get("node_config", {})
            for name, t in cfg["available_node_types"].items()}
        provider_cfg["resources"] = {
            name: t["resources"]
            for name, t in cfg["available_node_types"].items()}
        # slices are provider nodes too (one node == one slice):
        # slice-level resources = per-host resources x host count
        for name, s in cfg.get("slices", {}).items():
            provider_cfg["node_configs"].setdefault(
                name, s.get("node_config", {}))
            hosts = hosts_for_topology(s["topology"])
            provider_cfg["resources"].setdefault(name, {
                k: v * hosts
                for k, v in s.get("host_resources", {}).items()})
        return GCETPUNodeProvider(provider_cfg, api=api,
                                  resolve_internal=state_resolver())
    if ptype == "fake":
        from ray_tpu.autoscaler.node_provider import FakeNodeProvider
        return FakeNodeProvider(provider_cfg.get("session_dir", "/tmp"),
                                provider_cfg)
    if ptype == "fake_slice":
        from ray_tpu.autoscaler.node_provider import FakeSliceProvider
        return FakeSliceProvider(provider_cfg.get("session_dir"),
                                 provider_cfg)
    raise ConfigError(f"unknown provider type {ptype!r}")


def slice_type_configs(cfg: Dict[str, Any]):
    """The ``slices:`` section of a validated config as
    :class:`~ray_tpu.autoscaler.slices.SliceTypeConfig` rows — what a
    SliceManager scales."""
    from ray_tpu.autoscaler.slices import SliceTypeConfig
    return [
        SliceTypeConfig(
            name,
            topology=s["topology"],
            host_resources=dict(s.get("host_resources", {"CPU": 1})),
            min_slices=int(s.get("min_slices", 0)),
            max_slices=int(s.get("max_slices", 4)))
        for name, s in cfg.get("slices", {}).items()]


def build_slice_manager(controller, cfg: Dict[str, Any],
                        provider: Optional[NodeProvider] = None,
                        idle_timeout_s: float = 3600.0,
                        drain_deadline_s: float = 30.0):
    """Construct the head's SliceManager from a validated cluster
    config — the wiring ``scripts/head`` runs automatically when the
    config has a ``slices:`` section (ROADMAP item 1: tests/drivers no
    longer build it by hand). Returns None when the config defines no
    slice types. Slices already created by the launcher are adopted,
    not re-acquired. The generous default ``idle_timeout_s`` keeps the
    monitor from releasing the ``count:`` slices ``up`` just created
    while a driver is still connecting."""
    types = slice_type_configs(cfg)
    if not types:
        return None
    from ray_tpu.autoscaler.slices import SliceManager
    provider = provider or _make_provider(cfg)
    return SliceManager(controller, provider, types,
                        idle_timeout_s=idle_timeout_s,
                        drain_deadline_s=drain_deadline_s)


def build_slice_arbiter(manager, cfg: Dict[str, Any]):
    """Construct the head's :class:`~ray_tpu.autoscaler.arbiter.
    SliceArbiter` over an already-built SliceManager when the config
    has an ``arbiter:`` section. The arbiter drives the manager's
    reconcile pass itself (``drive_manager=True``), so the head hands
    the ARBITER — not the manager — to its ``AutoscalerMonitor`` and
    one loop does both. Returns None when the config names no arbiter
    (the manager stays the monitor's target, wiring unchanged)."""
    section = cfg.get("arbiter")
    if manager is None or section is None:
        return None
    from ray_tpu.autoscaler.arbiter import ArbiterPolicy, SliceArbiter
    int_knobs = ("min_train_slices", "max_borrowed")
    policy = ArbiterPolicy(**{
        k: (int(v) if k in int_knobs else float(v))
        for k, v in section.items()})
    return SliceArbiter(manager, policy=policy, drive_manager=True)


def node_type_configs(cfg: Dict[str, Any]) -> List[NodeTypeConfig]:
    """Worker node types for the autoscaler: every type but the head."""
    return [
        NodeTypeConfig(name, t["resources"],
                       min_workers=t["min_workers"],
                       max_workers=t["max_workers"])
        for name, t in cfg["available_node_types"].items()
        if name != cfg["head_node_type"]]


class ClusterLauncher:
    """up/down/attach against a validated config. ``runner_factory``
    (ip, user -> CommandRunner) is injectable so tests record commands
    instead of opening SSH connections."""

    def __init__(self, cfg: Dict[str, Any],
                 provider: Optional[NodeProvider] = None,
                 api=None,
                 runner_factory: Optional[
                     Callable[[str, str], CommandRunner]] = None):
        self.cfg = cfg
        self.provider = provider or _make_provider(cfg, api=api)
        self.runner_factory = runner_factory or (
            lambda ip, user: SSHCommandRunner(
                ip, user, cfg["auth"].get("ssh_private_key")))

    # -------------------------------------------------------------- up
    def up(self) -> Dict[str, Any]:
        """Create (or reuse) the head slice, bootstrap every host VM of
        it, start the head daemon + autoscaler (reference:
        commands.get_or_create_head_node)."""
        head_type = self.cfg["head_node_type"]
        head = self._existing_head()
        created = False
        if head is None:
            head = self.provider.create_node(
                head_type,
                self.cfg["available_node_types"][head_type]["resources"])
            created = True
        if hasattr(self.provider, "wait_until_ready"):
            self.provider.wait_until_ready(head)
        endpoints = self._endpoints(head)
        head_ip = endpoints[0] if endpoints else None
        user = self.cfg["auth"]["ssh_user"]
        cmds = list(self.cfg["setup_commands"])
        start = [c.format(head_ip=head_ip or "127.0.0.1",
                          cluster_name=self.cfg["cluster_name"])
                 for c in self.cfg["head_start_commands"]]
        # worker hosts of a multi-host head slice join as workers
        for i, ip in enumerate(endpoints):
            runner = self.runner_factory(ip, user)
            for cmd in cmds + (start if i == 0 else [
                    c.format(head_ip=head_ip, cluster_name=self
                             .cfg["cluster_name"])
                    for c in self.cfg["worker_start_commands"]]):
                runner.run(cmd)
        # bring up the configured gang slices whole (the SliceManager
        # running on the head scales them from there)
        slice_ids: List[str] = []
        if self.cfg.get("slices") and \
                hasattr(self.provider, "create_slice"):
            for name, s in self.cfg["slices"].items():
                for _ in range(int(s.get("count", 1))):
                    slice_ids.append(self.provider.create_slice(
                        name, s.get("topology", ""),
                        s.get("host_resources")))
        logger.info("cluster %s is up (head=%s ip=%s slices=%d)",
                    self.cfg["cluster_name"], head, head_ip,
                    len(slice_ids))
        return {"head_node": head, "head_ip": head_ip,
                "created": created, "slices": slice_ids}

    def _existing_head(self) -> Optional[str]:
        head_type = self.cfg["head_node_type"]
        for nid in self.provider.non_terminated_nodes():
            try:
                if self.provider.node_type(nid) == head_type:
                    return nid
            except KeyError:
                continue
        return None

    def _endpoints(self, node_id: str) -> List[str]:
        if hasattr(self.provider, "host_endpoints"):
            eps = self.provider.host_endpoints(node_id)
            out = []
            for e in eps:
                access = e.get("accessConfig") or {}
                out.append(access.get("externalIp") or e.get("ipAddress"))
            return [ip for ip in out if ip]
        return []

    # ------------------------------------------------------------ down
    def down(self, keep_head: bool = False) -> List[str]:
        """Terminate every provider node of this cluster (reference:
        commands.teardown_cluster; workers first, head last so state
        queries keep working during the drain)."""
        head_type = self.cfg["head_node_type"]
        nodes = self.provider.non_terminated_nodes()
        workers = [n for n in nodes
                   if self._type_of(n) != head_type]
        heads = [n for n in nodes if self._type_of(n) == head_type]
        gone = []
        for nid in workers + ([] if keep_head else heads):
            self.provider.terminate_node(nid)
            gone.append(nid)
        logger.info("cluster %s: terminated %d node(s)",
                    self.cfg["cluster_name"], len(gone))
        return gone

    def _type_of(self, nid: str) -> Optional[str]:
        try:
            return self.provider.node_type(nid)
        except KeyError:
            return None

    # ---------------------------------------------------------- attach
    def attach_command(self) -> List[str]:
        """The ssh invocation for an interactive shell on the head."""
        head = self._existing_head()
        if head is None:
            raise RuntimeError(
                f"cluster {self.cfg['cluster_name']} has no head node; "
                f"run `ray-tpu up` first")
        if hasattr(self.provider, "wait_until_ready"):
            self.provider.wait_until_ready(head, timeout_s=60)
        ips = self._endpoints(head)
        if not ips:
            raise RuntimeError(f"head node {head} has no endpoints yet")
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
        key = self.cfg["auth"].get("ssh_private_key")
        if key:
            cmd += ["-i", key]
        cmd.append(f"{self.cfg['auth']['ssh_user']}@{ips[0]}")
        return cmd


# ------------------------------------------------------- local launcher
class LocalClusterLauncher:
    """``ray-tpu up/down`` against the LOCAL fake providers: the head
    is a local daemon (``ray_tpu.scripts.head``) and every slice's
    host VMs are local node-manager processes (``FakeSliceProvider``)
    — the zero-cloud round-trip the subprocess tests drive, and the
    laptop-scale way to try gang scheduling end to end.

    State lives under the session dir (``provider.session_dir`` in the
    YAML, default ``/tmp/ray_tpu/<cluster_name>``): the head pid in
    ``launcher_state.json`` and the slice inventory in the provider's
    own ``fake_slices.json`` — so ``down`` from a fresh process finds
    everything ``up`` started."""

    STATE_FILE = "launcher_state.json"

    def __init__(self, cfg: Dict[str, Any]):
        self.cfg = cfg
        self.session_dir = cfg["provider"].get("session_dir") or \
            os.path.join("/tmp/ray_tpu", cfg["cluster_name"])

    def _state_path(self) -> str:
        return os.path.join(self.session_dir, self.STATE_FILE)

    def _load_state(self) -> dict:
        try:
            with open(self._state_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _head_alive(self) -> bool:
        pid = self._load_state().get("head_pid")
        if not pid:
            return False
        try:
            os.kill(pid, 0)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    def _provider(self):
        from ray_tpu.autoscaler.node_provider import FakeSliceProvider
        pcfg = dict(self.cfg["provider"])
        pcfg["session_dir"] = self.session_dir
        return FakeSliceProvider(self.session_dir, pcfg)

    # -------------------------------------------------------------- up
    def up(self, wait_ready_s: float = 30.0) -> Dict[str, Any]:
        os.makedirs(self.session_dir, exist_ok=True)
        head_type = self.cfg["head_node_type"]
        head_res = self.cfg["available_node_types"][head_type][
            "resources"]
        # persist the normalized config where the head daemon (and a
        # later `down` from a fresh process) can find it: the head
        # auto-starts the SliceManager monitor from its slices: section
        cfg_path = os.path.join(self.session_dir, "cluster.yaml")
        cfg_copy = dict(self.cfg)
        cfg_copy["provider"] = dict(self.cfg["provider"],
                                    session_dir=self.session_dir)
        with open(cfg_path, "w") as f:
            yaml.safe_dump(cfg_copy, f)
        created_head = False
        if not self._head_alive():
            cmd = [sys.executable, "-m", "ray_tpu.scripts.head",
                   "--session-dir", self.session_dir,
                   "--num-cpus", str(head_res.get("CPU", 1)),
                   "--initial-workers", "1",
                   "--cluster-config", cfg_path]
            with open(os.path.join(self.session_dir, "head.log"),
                      "ab") as log:
                proc = subprocess.Popen(
                    cmd, stdout=log, stderr=subprocess.STDOUT,
                    start_new_session=True)
            with open(self._state_path(), "w") as f:
                json.dump({"head_pid": proc.pid}, f)
            created_head = True
            # ready == controller socket bound AND session.json
            # written (init writes the json after the bind; drivers
            # need both to connect)
            markers = [os.path.join(self.session_dir, p)
                       for p in ("controller.sock", "session.json")]
            deadline = time.monotonic() + wait_ready_s
            while not all(os.path.exists(p) for p in markers):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"head daemon exited rc={proc.returncode} "
                        f"(see {self.session_dir}/head.log)")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"head not ready after {wait_ready_s}s")
                time.sleep(0.1)
        provider = self._provider()
        slice_ids: List[str] = []
        for name, s in self.cfg.get("slices", {}).items():
            for _ in range(int(s.get("count", 1))):
                slice_ids.append(provider.create_slice(
                    name, s["topology"], s.get("host_resources")))
        logger.info("local cluster %s up: session=%s slices=%s",
                    self.cfg["cluster_name"], self.session_dir,
                    slice_ids)
        return {"session_dir": self.session_dir,
                "head_pid": self._load_state().get("head_pid"),
                "created": created_head, "slices": slice_ids}

    # ------------------------------------------------------------ down
    def down(self, keep_head: bool = False) -> Dict[str, Any]:
        provider = self._provider()
        gone = list(provider.non_terminated_nodes())
        for sid in gone:
            provider.delete_slice(sid)
        head_pid = self._load_state().get("head_pid")
        if head_pid and not keep_head:
            try:
                os.kill(head_pid, signal.SIGTERM)
                for _ in range(100):
                    try:
                        # reap if it's our own child, else a zombie
                        # would keep answering signal 0 forever
                        os.waitpid(head_pid, os.WNOHANG)
                    except ChildProcessError:
                        pass
                    os.kill(head_pid, 0)
                    time.sleep(0.1)
                else:
                    os.kill(head_pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                os.remove(self._state_path())
            except OSError:
                pass
        logger.info("local cluster %s down: %d slice(s) terminated",
                    self.cfg["cluster_name"], len(gone))
        return {"terminated": gone, "head_pid": head_pid}


def make_launcher(cfg: Dict[str, Any], **kwargs):
    """The right launcher for the config's provider: local fakes get
    the process-spawning round-trip, clouds get the SSH bootstrap."""
    if cfg["provider"]["type"].startswith("fake"):
        return LocalClusterLauncher(cfg)
    return ClusterLauncher(cfg, **kwargs)
