"""StandardAutoscaler: demand-driven scale-up, idle-driven scale-down.

Reference: ``python/ray/autoscaler/_private/autoscaler.py:172``
(StandardAutoscaler.update: read LoadMetrics, bin-pack pending demand
onto available node types, launch up to max, terminate idle) and
``_private/monitor.py:126`` (the loop driving update). Differences by
design: demand comes straight from the controller's ready queues and
pending placement groups (single scheduling authority — no LoadMetrics
gossip), and utilization joins on NodeID instead of ip addresses.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


class NodeTypeConfig:
    """One scalable node flavor (reference: available_node_types entries
    in the cluster YAML)."""

    def __init__(self, name: str, resources: Dict[str, float],
                 min_workers: int = 0, max_workers: int = 10):
        self.name = name
        self.resources = dict(resources)
        self.min_workers = min_workers
        self.max_workers = max_workers


def _fits(node_resources: Dict[str, float],
          demand: Dict[str, float]) -> bool:
    return all(node_resources.get(k, 0.0) >= v
               for k, v in demand.items() if v > 0)


def collect_demand_snapshot(controller) -> dict:
    """Controller-loop-thread: pending demand + per-node busyness.
    Shared by the v1 StandardAutoscaler, the v2 reconciler, and the
    SliceManager (which consumes ``slice_demand``)."""
    c = controller
    demand: List[Dict[str, float]] = []
    slice_demand: List[dict] = []
    for key, q in c.ready_queues.items():
        for tid in q:
            t = c.tasks.get(tid)
            if t is not None and t.state == "QUEUED":
                demand.append(c._sched_res(t.spec))
    for _, spec in c.pending_pgs:
        if spec.strategy in ("SLICE_PACK", "SLICE_SPREAD"):
            # slice-spanning gangs demand a WHOLE slice, not loose
            # nodes: surfaced separately so the node autoscaler never
            # launches singles for them (autoscaler/slices.py consumes)
            slice_demand.append({
                "hosts": len(spec.bundles)
                if spec.strategy == "SLICE_SPREAD" else 1,
                "bundles": [dict(b.resources) for b in spec.bundles]})
        else:
            demand.extend(b.resources for b in spec.bundles)
    busy_nodes = set()
    for lease in c.leases.values():
        busy_nodes.add(lease.node_b)
    # direct-transport worker leases create no controller lease but
    # their workers execute driver-pushed tasks — the node is busy
    for nb in getattr(c, "_lease_node", {}).values():
        busy_nodes.add(nb)
    for info in c.actors.values():
        if info.state != "DEAD" and info.node_id is not None:
            busy_nodes.add(info.node_id.binary())
    alive = {nb for nb, n in c.nodes.items() if n.alive}
    return {"demand": demand, "slice_demand": slice_demand,
            "busy_nodes": busy_nodes, "alive_nodes": alive}


def drain_node_if_idle(controller, node_b: bytes) -> bool:
    """Controller-loop-thread: mark draining unless work holds the
    node. Returns True when the node is safe to terminate."""
    return drain_nodes_if_idle(controller, [node_b])


def drain_nodes_if_idle(controller, node_bs: List[bytes]) -> bool:
    """Controller-loop-thread, slice-granular: drain ALL the given nodes
    atomically, or none — a TPU pod slice terminates as a unit, so one
    busy host VM vetoes the whole slice's termination (reference:
    DrainNode precedes termination; the gang extension is ours)."""
    from ray_tpu.core.ids import NodeID
    c = controller
    targets = set(node_bs)
    busy = any(l.node_b in targets for l in c.leases.values()) \
        or any(nb in targets
               for nb in getattr(c, "_lease_node", {}).values()) \
        or any(
            info.state != "DEAD" and info.node_id is not None
            and info.node_id.binary() in targets
            for info in c.actors.values())
    if busy:
        return False
    for node_b in targets:
        c.scheduler.set_draining(NodeID(node_b), True)
    return True


class StandardAutoscaler:
    def __init__(self, controller, provider: NodeProvider,
                 node_types: List[NodeTypeConfig],
                 idle_timeout_s: float = 60.0,
                 max_launch_batch: int = 5):
        self.controller = controller
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.idle_timeout_s = idle_timeout_s
        self.max_launch_batch = max_launch_batch
        self._idle_since: Dict[str, float] = {}  # provider node id -> ts

    # ------------------------------------------------------------ update
    def update(self) -> Dict[str, Any]:
        """One reconcile pass; returns what it did (for tests/monitor
        logs). Reference: StandardAutoscaler.update."""
        snap = self.controller.call_on_loop(self._snapshot)
        launched = self._scale_up(snap)
        terminated = self._scale_down(snap)
        return {"launched": launched, "terminated": terminated,
                "pending_demand": len(snap["demand"])}

    def _snapshot(self) -> dict:
        return collect_demand_snapshot(self.controller)

    def _provider_nodes_by_type(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {name: [] for name in self.node_types}
        for nid in self.provider.non_terminated_nodes():
            out.setdefault(self.provider.node_type(nid), []).append(nid)
        return out

    def _scale_up(self, snap: dict) -> List[str]:
        """First-fit bin-pack unplaceable demand onto hypothetical new
        nodes (reference: resource_demand_scheduler.get_nodes_to_launch,
        simplified to first-fit like its binpacking core)."""
        by_type = self._provider_nodes_by_type()
        launched: List[str] = []
        # eagerly maintain min_workers (reference: the autoscaler launches
        # to min_workers even with zero demand)
        for t in self.node_types.values():
            while len(by_type.get(t.name, ())) + \
                    sum(1 for x in launched
                        if self.provider.node_type(x) == t.name) \
                    < t.min_workers:
                launched.append(self.provider.create_node(
                    t.name, t.resources))
        demand = [d for d in snap["demand"] if d]
        if not demand:
            return launched
        planned: List[NodeTypeConfig] = []
        # capacity already launched but not yet registered (starting
        # nodes are invisible to the scheduler, so queued demand they
        # will absorb must not trigger duplicate launches)
        planned_room: List[Dict[str, float]] = [
            dict(self.provider.node_resources(nid))
            for nids in by_type.values() for nid in nids
            if not any(i in snap["alive_nodes"]
                       for i in self.provider.internal_ids(nid))]
        for d in demand:
            placed = False
            for room in planned_room:
                if _fits(room, d):
                    for k, v in d.items():
                        room[k] = room.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            for t in self.node_types.values():
                existing = len(by_type.get(t.name, ()))
                already = sum(1 for p in planned if p.name == t.name)
                if existing + already >= t.max_workers:
                    continue
                if _fits(t.resources, d):
                    planned.append(t)
                    room = dict(t.resources)
                    for k, v in d.items():
                        room[k] = room.get(k, 0.0) - v
                    planned_room.append(room)
                    break
            # demand no type can satisfy is skipped (the reference logs
            # an infeasible warning; scheduler keeps it queued)
        for t in planned[:self.max_launch_batch]:
            nid = self.provider.create_node(t.name, t.resources)
            logger.info("autoscaler: launched %s (%s)", nid, t.name)
            launched.append(nid)
        return launched

    def _scale_down(self, snap: dict) -> List[str]:
        now = time.monotonic()
        terminated = []
        by_type = self._provider_nodes_by_type()
        for t in self.node_types.values():
            nodes = by_type.get(t.name, [])
            for nid in nodes:
                internals = self.provider.internal_ids(nid)
                # a multi-host slice has joined when EVERY expected host
                # VM is alive (partially-joined slices are still
                # starting); one busy host makes the whole slice busy —
                # slices terminate as a unit
                joined = len(internals) >= \
                    self.provider.expected_internal_count(nid) and \
                    bool(internals) and all(
                        i in snap["alive_nodes"] for i in internals)
                busy = any(i in snap["busy_nodes"] for i in internals)
                if busy or not joined:
                    # not-yet-joined nodes are starting up, not idle
                    self._idle_since.pop(nid, None)
                    continue
                since = self._idle_since.setdefault(nid, now)
                if now - since < self.idle_timeout_s:
                    continue
                if len(nodes) - len([x for x in terminated
                                     if x in nodes]) <= t.min_workers:
                    continue
                # drain atomically on the controller loop: mark every
                # host unschedulable iff all are still idle (reference:
                # DrainNode precedes termination) — closes the race
                # where a lease lands between our snapshot and the
                # SIGTERM, and keeps slice termination all-or-nothing
                if not self.controller.call_on_loop(
                        lambda ids=internals:
                        drain_nodes_if_idle(self.controller, ids)):
                    self._idle_since.pop(nid, None)
                    continue
                logger.info("autoscaler: terminating idle node %s", nid)
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
                terminated.append(nid)
        return terminated


class AutoscalerMonitor:
    """Background loop driving update() (reference: monitor.py:126).
    Drives anything with an ``update()`` — v1, v2, or a SliceManager.

    Every wait goes through the stop Event (never a bare
    ``time.sleep``), so :meth:`stop` interrupts a sleeping loop
    promptly. Repeated ``update()`` failures back off with the shared
    jittered exponential (``util/backoff.py``) instead of hammering a
    broken provider at the fixed interval; one success resets it."""

    def __init__(self, autoscaler,
                 interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from ray_tpu.util.backoff import ExponentialBackoff
        # equal jitter keeps a floor of interval/2 — a failing pass
        # must never retry faster than a healthy one polls
        self._backoff = ExponentialBackoff(
            base=max(0.1, interval_s), cap=max(60.0, interval_s),
            jitter="equal")

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler-monitor", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        delay = self.interval_s
        while not self._stop.wait(delay):
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler update failed")
                delay = self._backoff.next_delay()
            else:
                self._backoff.reset()
                delay = self.interval_s

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
