"""GKE/KubeRay-shaped node provider: joins the autoscaler to a
Kubernetes-managed TPU fleet.

Reference: ``python/ray/autoscaler/_private/kuberay/node_provider.py``
(KubeRayNodeProvider — the autoscaler never creates cloud instances
itself; it PATCHes the RayCluster custom resource's
``workerGroupSpecs[i].replicas`` and lets the KubeRay operator reconcile
pods, scaling down via the ``workersToDelete`` protocol so the operator
deletes the *specific* pods the autoscaler drained).

TPU-native mapping, consistent with :mod:`ray_tpu.autoscaler.gce`: one
provider node is one TPU pod SLICE — here one replica of a worker group
whose pod template requests a ``google.com/tpu`` node-pool. A
``v5litepod-64`` demand bumps one workergroup's replicas by one; the
operator schedules the slice's host pods, which run ``ray-tpu start``
and join the cluster carrying the provider-node label.

The REST transport is injectable (``request_fn``) so tests drive the
full provider against a mock of the Kubernetes API; the production
default reads the in-cluster service-account token.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

#: pod labels the operator stamps / the provider filters on (KubeRay's
#: ray.io/* label family, TPU-native names)
LABEL_CLUSTER = "ray-tpu/cluster"
LABEL_GROUP = "ray-tpu/group"
LABEL_NODE_ID = "ray-tpu/node-id"

GROUP_VERSION = "ray-tpu.io/v1"
PLURAL = "raytpuclusters"


class K8sApiError(RuntimeError):
    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


class K8sApiClient:
    """Minimal Kubernetes REST client (in-cluster auth).

    ``request_fn(method, path, body_dict_or_None) -> dict`` is the whole
    transport; tests inject a fake. ``path`` is the API path relative to
    the apiserver root (e.g. ``/api/v1/namespaces/x/pods``).
    """

    def __init__(self, namespace: str,
                 request_fn: Optional[Callable[..., dict]] = None,
                 host: str = "https://kubernetes.default.svc",
                 sleep_fn: Callable[[float], None] = time.sleep,
                 max_retries: int = 5):
        self.namespace = namespace
        self.host = host
        self._request = request_fn or self._urllib_request
        self._sleep = sleep_fn
        self._max_retries = max_retries
        self._token: Optional[str] = None
        self._rng = __import__("random").Random()

    def _get_token(self) -> str:
        if self._token is None:
            with open(f"{SA_DIR}/token") as f:
                self._token = f.read().strip()
        return self._token

    def _urllib_request(self, method: str, path: str,
                        body: Optional[dict]) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        content_type = "application/json"
        if method == "PATCH":
            # RFC 6902 JSON patch: what KubeRay's autoscaler uses for
            # replicas/workersToDelete updates
            content_type = "application/json-patch+json"
        attempt = 0
        while True:
            req = urllib.request.Request(
                self.host + path, data=data, method=method,
                headers={"Authorization": f"Bearer {self._get_token()}",
                         "Content-Type": content_type})
            try:
                import ssl
                ctx = ssl.create_default_context(cafile=f"{SA_DIR}/ca.crt")
                with urllib.request.urlopen(req, timeout=60,
                                            context=ctx) as resp:
                    payload = resp.read()
                return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")[:500]
                if e.code not in (429, 500, 502, 503, 504) \
                        or attempt >= self._max_retries:
                    raise K8sApiError(
                        f"k8s API {method} {path} -> {e.code}: {detail}",
                        status=e.code) from e
            except urllib.error.URLError as e:
                if attempt >= self._max_retries:
                    raise K8sApiError(
                        f"k8s API {method} {path} unreachable: "
                        f"{e.reason}") from e
            # shared retry shape (util/backoff.py): same envelope as
            # the historical inline formula — equal jitter, base 1s,
            # 30s cap
            from ray_tpu.util.backoff import backoff_delay
            self._sleep(backoff_delay(attempt, base=1.0, cap=30.0,
                                      jitter="equal", rng=self._rng))
            attempt += 1

    # ----------------------------------------------------------- objects
    def get_cluster_cr(self, name: str) -> dict:
        return self._request(
            "GET", f"/apis/{GROUP_VERSION}/namespaces/{self.namespace}"
                   f"/{PLURAL}/{name}", None)

    def patch_cluster_cr(self, name: str, patch: List[dict]) -> dict:
        return self._request(
            "PATCH", f"/apis/{GROUP_VERSION}/namespaces/{self.namespace}"
                     f"/{PLURAL}/{name}", patch)

    def list_pods(self, label_selector: str) -> List[dict]:
        out: List[dict] = []
        token = ""
        while True:
            path = (f"/api/v1/namespaces/{self.namespace}/pods"
                    f"?labelSelector={label_selector}")
            if token:
                path += f"&continue={token}"
            resp = self._request("GET", path, None)
            out.extend(resp.get("items", []))
            token = (resp.get("metadata") or {}).get("continue") or ""
            if not token:
                return out


class GKETPUNodeProvider(NodeProvider):
    """NodeProvider over KubeRay-style worker groups of TPU slices.

    provider_config keys:
      namespace, cluster_name     — the RayTPUCluster CR to drive
      groups: {node_type: group}  — worker-group name per node type (the
                                    CR's workerGroupSpecs[].groupName)
      resources: {node_type: {..}} — slice-level resources per type
    """

    def __init__(self, provider_config: Dict[str, Any],
                 api: Optional[K8sApiClient] = None,
                 resolve_internal: Optional[
                     Callable[[str], List[bytes]]] = None):
        super().__init__(provider_config)
        self.namespace = provider_config["namespace"]
        self.cluster_name = provider_config["cluster_name"]
        self.api = api or K8sApiClient(self.namespace)
        self.groups: Dict[str, str] = dict(
            provider_config.get("groups", {}))
        self._type_by_group = {g: t for t, g in self.groups.items()}
        self._resources: Dict[str, Dict[str, float]] = {
            k: dict(v)
            for k, v in (provider_config.get("resources") or {}).items()}
        self._resolve_internal = resolve_internal or (lambda _nid: [])
        self._lock = threading.Lock()
        #: node_id -> {type, group}; includes replicas we bumped whose
        #: pods have not appeared yet (pending inventory, so demand that
        #: a booting slice will absorb doesn't double-launch)
        self._meta: Dict[str, dict] = {}
        self._creating: Dict[str, float] = {}
        self._pods_cache: Optional[List[dict]] = None
        self._pods_cache_at = 0.0
        self.pods_cache_ttl_s = float(
            provider_config.get("pods_cache_ttl_s", 5.0))
        #: (slice id, annotation) pairs already reported as drains
        self._maintenance_seen: set = set()

    # ------------------------------------------------------------ helpers
    def _group_index(self, cr: dict, group: str) -> int:
        specs = cr.get("spec", {}).get("workerGroupSpecs", [])
        for i, s in enumerate(specs):
            if s.get("groupName") == group:
                return i
        raise KeyError(f"worker group {group!r} not in CR "
                       f"{self.cluster_name} (has "
                       f"{[s.get('groupName') for s in specs]})")

    def _cluster_pods(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            if self._pods_cache is not None and \
                    now - self._pods_cache_at < self.pods_cache_ttl_s:
                return self._pods_cache
        sel = f"{LABEL_CLUSTER}={self.cluster_name}"
        pods = self.api.list_pods(sel)
        live = [p for p in pods
                if (p.get("status", {}).get("phase")
                    in ("Pending", "Running"))
                and not p.get("metadata", {}).get("deletionTimestamp")]
        with self._lock:
            self._pods_cache = live
            self._pods_cache_at = now
            for p in live:
                labels = p["metadata"].get("labels", {})
                nid = labels.get(LABEL_NODE_ID)
                if nid:
                    self._creating.pop(nid, None)
                    if nid not in self._meta:
                        # pods carry the GROUP label; map back to the
                        # configured node TYPE (a restarted provider
                        # rediscovering slices must type them correctly
                        # or the autoscaler double-launches)
                        group = labels.get(LABEL_GROUP, "")
                        self._meta[nid] = {
                            "type": self._type_by_group.get(group,
                                                            group),
                            "group": group}
        return live

    def _invalidate_pods(self) -> None:
        with self._lock:
            self._pods_cache = None

    # ------------------------------------------------------------ listing
    def non_terminated_nodes(self) -> List[str]:
        pods = self._cluster_pods()
        listed = []
        seen = set()
        for p in pods:
            nid = p["metadata"].get("labels", {}).get(LABEL_NODE_ID)
            if nid and nid not in seen:
                seen.add(nid)
                listed.append(nid)
        with self._lock:
            pending = [nid for nid in self._creating if nid not in seen]
        return listed + pending

    def node_type(self, node_id: str) -> str:
        with self._lock:
            meta = self._meta.get(node_id)
        if meta is None:
            raise KeyError(f"unknown provider node {node_id}")
        return meta["type"]

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return dict(self._resources.get(self.node_type(node_id), {}))

    # ----------------------------------------------------------- creation
    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        """Scale the node type's worker group up by one replica. The
        operator creates the slice's pods; they carry our node-id label
        via the group's pod template (the CR templating substitutes
        the per-replica node id, mirroring KubeRay's replica hostnames).
        """
        group = self.groups.get(node_type)
        if group is None:
            raise KeyError(
                f"no worker group for node type {node_type!r} "
                f"(configured: {sorted(self.groups)})")
        node_id = f"ray-{self.cluster_name}-{node_type}-" \
                  f"{uuid.uuid4().hex[:8]}"
        cr = self.api.get_cluster_cr(self.cluster_name)
        idx = self._group_index(cr, group)
        replicas = int(cr["spec"]["workerGroupSpecs"][idx]
                       .get("replicas", 0))
        self.api.patch_cluster_cr(self.cluster_name, [
            {"op": "replace",
             "path": f"/spec/workerGroupSpecs/{idx}/replicas",
             "value": replicas + 1},
            {"op": "add",
             "path": f"/spec/workerGroupSpecs/{idx}/pendingNodeIds/-",
             "value": node_id},
        ])
        with self._lock:
            self._creating[node_id] = time.monotonic()
            self._meta[node_id] = {"type": node_type, "group": group}
        self._invalidate_pods()
        logger.info("gke: scaled up group %s for %s (replica node %s)",
                    group, node_type, node_id)
        return node_id

    # -------------------------------------------------------- termination
    def terminate_node(self, node_id: str) -> None:
        """KubeRay scale-down protocol: name the node in the group's
        ``workersToDelete`` AND decrement replicas in one patch, so the
        operator removes exactly this slice (not an arbitrary replica).
        Local bookkeeping is dropped only AFTER the API accepted the
        patch — popping first would make a failed terminate permanently
        unretryable (the no-op double-terminate path) and leak the
        slice."""
        with self._lock:
            meta = self._meta.get(node_id)
        if meta is None:
            return
        cr = self.api.get_cluster_cr(self.cluster_name)
        idx = self._group_index(cr, meta["group"])
        spec = cr["spec"]["workerGroupSpecs"][idx]
        replicas = int(spec.get("replicas", 0))
        self.api.patch_cluster_cr(self.cluster_name, [
            {"op": "replace",
             "path": f"/spec/workerGroupSpecs/{idx}/replicas",
             "value": max(0, replicas - 1)},
            {"op": "add",
             "path": f"/spec/workerGroupSpecs/{idx}"
                     f"/scaleStrategy/workersToDelete/-",
             "value": node_id},
        ])
        with self._lock:
            self._meta.pop(node_id, None)
            self._creating.pop(node_id, None)
        self._invalidate_pods()
        logger.info("gke: scaled down %s (group %s)", node_id,
                    meta["group"])

    # ----------------------------------------------------------- identity
    def internal_ids(self, node_id: str) -> List[bytes]:
        return list(self._resolve_internal(node_id))

    def internal_id(self, node_id: str) -> Optional[bytes]:
        ids = self.internal_ids(node_id)
        return ids[0] if ids else None

    def expected_internal_count(self, node_id: str) -> int:
        """Host count = the slice's pods carrying this node id."""
        n = 0
        for p in self._cluster_pods():
            if p["metadata"].get("labels", {}).get(LABEL_NODE_ID) \
                    == node_id:
                n += 1
        return max(1, n)

    # ---- slice-granular API: one workergroup replica IS one slice ----
    def create_slice(self, slice_type: str, topology: str = "",
                     host_resources: Optional[Dict[str, float]] = None
                     ) -> str:
        return self.create_node(
            slice_type,
            dict(host_resources
                 or self._resources.get(slice_type, {})))

    def delete_slice(self, slice_id: str) -> None:
        self.terminate_node(slice_id)

    def slice_hosts(self, slice_id: str) -> List[str]:
        return [p["metadata"].get("name", "")
                for p in self._cluster_pods()
                if p["metadata"].get("labels", {}).get(LABEL_NODE_ID)
                == slice_id]

    def maintenance_events(self) -> List[dict]:
        """Kubernetes drain notices: a pod annotated
        ``ray-tpu/maintenance`` (what a node-drain webhook or the
        operator stamps ahead of TPU maintenance) flags its whole
        slice for a preemption-aware drain. Each (slice, annotation)
        pair is reported once."""
        out: List[dict] = []
        for p in self._cluster_pods():
            md = p.get("metadata", {})
            nid = md.get("labels", {}).get(LABEL_NODE_ID)
            notice = (md.get("annotations") or {}).get(
                "ray-tpu/maintenance")
            if not nid or notice is None:
                continue
            key = (nid, str(notice))
            with self._lock:
                if key in self._maintenance_seen:
                    continue
                self._maintenance_seen.add(key)
            out.append({"slice_id": nid, "kind": "maintenance",
                        "event_id": f"gke-{len(self._maintenance_seen)}"})
        return out
