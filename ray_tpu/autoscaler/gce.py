"""GCE TPU-VM node provider: provisions real TPU pod slices.

Reference: ``python/ray/autoscaler/_private/gcp/node.py:618`` (GCPTPU —
create/list/delete/labels against the Cloud TPU REST API with
long-running-operation polling) and ``gcp/node_provider.py`` (the
NodeProvider plugin joining that API to the autoscaler). The TPU-native
redesign differs structurally: here **one provider node is one TPU pod
slice** — the atomic gang unit the scheduler reasons about
(``TPU-{type}-head`` resources) — never an individual VM, so a
``v5litepod-64`` demand creates exactly one slice whose 16 host VMs all
join the cluster, and termination deletes the whole slice atomically.

The REST transport is injectable (``request_fn``) so tests drive the
full provider against a mock of the TPU API; production default uses
urllib with a GCE metadata-server OAuth token.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)

TPU_API_ROOT = "https://tpu.googleapis.com/v2"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")

#: provider-owned labels stamped on every slice we create
LABEL_CLUSTER = "ray-tpu-cluster"
LABEL_NODE_TYPE = "ray-tpu-node-type"
LABEL_NODE_ID = "ray-tpu-node-id"

#: TPU node states that count as "gone" (reference: GCPTPUNode.is_terminated
#: treats anything past READY/CREATING/STARTING/REPAIRING as terminated)
_LIVE_STATES = {"CREATING", "READY", "STARTING", "REPAIRING", "RESTARTING"}


class TPUApiError(RuntimeError):
    """An error surfaced by the Cloud TPU API (HTTP or operation error)."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


def _default_token_fn() -> Dict[str, Any]:
    """Fetch an access token from the GCE metadata server. Returns the
    raw token payload ({access_token, expires_in, ...})."""
    req = urllib.request.Request(
        METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


#: HTTP statuses worth retrying (reference: gcp/node.py:618's
#: has_retriable_http_code — rate limits and transient server errors)
_RETRYABLE_STATUSES = (429, 500, 502, 503, 504)


class TPUApiClient:
    """Thin REST client for the Cloud TPU v2 API.

    ``request_fn(method, url, body_dict_or_None) -> dict`` is the whole
    transport; tests inject a fake, production uses `_urllib_request` —
    which retries 429/5xx and network errors with exponential backoff +
    jitter, caches the metadata token until shortly before expiry, and
    refreshes it once on a 401 (reference: gcp/node.py retry semantics).
    """

    def __init__(self, project: str, zone: str,
                 request_fn: Optional[Callable[..., dict]] = None,
                 token_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 max_retries: int = 5):
        self.project = project
        self.zone = zone
        self._token_fn = token_fn or _default_token_fn
        self._request = request_fn or self._urllib_request
        self._sleep = sleep_fn
        self._max_retries = max_retries
        self._token: Optional[str] = None
        self._token_expiry = 0.0
        self._rng = __import__("random").Random()

    @property
    def parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    # ------------------------------------------------------------- token
    def _get_token(self) -> str:
        if self._token is None or time.monotonic() >= self._token_expiry:
            payload = self._token_fn()
            if isinstance(payload, str):
                # legacy injectable token_fns return the bare token
                self._token, self._token_expiry = payload, float("inf")
            else:
                self._token = payload["access_token"]
                # refresh 60s early so in-flight requests never carry a
                # token that expires mid-call
                self._token_expiry = time.monotonic() + max(
                    30.0, float(payload.get("expires_in", 3600)) - 60.0)
        return self._token

    def _invalidate_token(self) -> None:
        self._token = None
        self._token_expiry = 0.0

    def _backoff(self, attempt: int) -> None:
        # shared retry shape (util/backoff.py): exponential, capped;
        # equal jitter keeps the floor the transport tests assert on
        from ray_tpu.util.backoff import backoff_delay
        self._sleep(backoff_delay(attempt, base=1.0, cap=30.0,
                                  jitter="equal", rng=self._rng))

    def _urllib_request(self, method: str, url: str,
                        body: Optional[dict]) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        attempt = 0
        refreshed = False
        while True:
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={"Authorization": f"Bearer {self._get_token()}",
                         "Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")[:500]
                if e.code == 401 and not refreshed:
                    # token expired server-side (clock skew, revocation):
                    # refresh once and retry immediately
                    refreshed = True
                    self._invalidate_token()
                    continue
                if e.code not in _RETRYABLE_STATUSES \
                        or attempt >= self._max_retries:
                    raise TPUApiError(
                        f"TPU API {method} {url} -> {e.code}: {detail}",
                        status=e.code) from e
                logger.warning("gce: %s %s -> %s (attempt %d); retrying",
                               method, url, e.code, attempt + 1)
            except urllib.error.URLError as e:
                # transport-level failure (DNS, conn reset): retryable
                if attempt >= self._max_retries:
                    raise TPUApiError(
                        f"TPU API {method} {url} unreachable: "
                        f"{e.reason}") from e
                logger.warning("gce: %s %s unreachable (%s, attempt %d);"
                               " retrying", method, url, e.reason,
                               attempt + 1)
            self._backoff(attempt)
            attempt += 1

    # ------------------------------------------------------------ nodes
    def create_node(self, node_id: str, body: dict) -> dict:
        """Returns a long-running operation (reference: nodes.create)."""
        url = f"{TPU_API_ROOT}/{self.parent}/nodes?nodeId={node_id}"
        return self._request("POST", url, body)

    def list_nodes(self) -> List[dict]:
        url = f"{TPU_API_ROOT}/{self.parent}/nodes"
        out: List[dict] = []
        page_token = None
        while True:
            page_url = url + (f"?pageToken={page_token}" if page_token
                              else "")
            resp = self._request("GET", page_url, None)
            out.extend(resp.get("nodes", []))
            page_token = resp.get("nextPageToken")
            if not page_token:
                return out

    def get_node(self, name: str) -> dict:
        return self._request("GET", f"{TPU_API_ROOT}/{name}", None)

    def delete_node(self, name: str) -> dict:
        return self._request("DELETE", f"{TPU_API_ROOT}/{name}", None)

    def get_operation(self, name: str) -> dict:
        return self._request("GET", f"{TPU_API_ROOT}/{name}", None)

    def wait_operation(self, operation: dict, timeout_s: float = 600.0,
                       poll_s: float = 5.0) -> dict:
        """Poll a long-running operation to completion (reference:
        GCPTPU.wait_for_operation). Polling rides the shared jittered
        backoff (util/backoff.py) growing to ``poll_s`` — fast first
        checks for quick operations, de-correlated steady-state polls
        for slow ones — through the injectable ``sleep_fn``."""
        from ray_tpu.util.backoff import ExponentialBackoff
        bo = ExponentialBackoff(base=min(1.0, poll_s), cap=poll_s,
                                jitter="equal", rng=self._rng)
        deadline = time.monotonic() + timeout_s
        op = operation
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TPUApiError(
                    f"operation {op.get('name')} timed out "
                    f"after {timeout_s}s")
            self._sleep(bo.next_delay())
            op = self.get_operation(op["name"])
        if "error" in op:
            # surface the operation metadata alongside the error: the
            # TPU API puts the target node + verb there, which is what
            # an operator needs to act on the failure
            meta = op.get("metadata") or {}
            ctx = ", ".join(f"{k}={meta[k]}" for k in
                            ("target", "verb", "apiVersion") if k in meta)
            raise TPUApiError(
                f"operation {op.get('name')} failed: {op['error']}"
                + (f" ({ctx})" if ctx else ""))
        return op


class GCETPUNodeProvider(NodeProvider):
    """NodeProvider over TPU pod slices.

    provider_config keys:
      project, zone, cluster_name       — identity
      node_configs: {node_type: body}   — per-type TPU node body template
                                          (acceleratorType, runtimeVersion,
                                          extra API fields)
      resources: {node_type: {..}}      — slice-level resources per type
      head_address                      — cluster head host:port baked
                                          into each slice's startup script
      startup_script                    — optional template; '{head}' and
                                          '{node_type}' are substituted
    """

    def __init__(self, provider_config: Dict[str, Any],
                 api: Optional[TPUApiClient] = None,
                 resolve_internal: Optional[
                     Callable[[str], List[bytes]]] = None):
        super().__init__(provider_config)
        self.project = provider_config["project"]
        self.zone = provider_config["zone"]
        self.cluster_name = provider_config["cluster_name"]
        self.api = api or TPUApiClient(self.project, self.zone)
        self.node_configs: Dict[str, dict] = dict(
            provider_config.get("node_configs", {}))
        self._resources: Dict[str, Dict[str, float]] = {
            k: dict(v)
            for k, v in (provider_config.get("resources") or {}).items()}
        # joins provider slices to controller NodeIDs; the launcher wires
        # this to the state API (workers register with a
        # provider-node-id label), tests inject directly
        self._resolve_internal = resolve_internal or (lambda _nid: [])
        self._lock = threading.Lock()
        #: node_id -> pending create operation (counted as live inventory
        #: so the autoscaler doesn't double-launch while a slice boots)
        self._creating: Dict[str, dict] = {}
        self._meta: Dict[str, dict] = {}   # node_id -> {type, name}
        self._list_cache: Optional[List[dict]] = None
        self._list_cache_at = 0.0
        self.list_cache_ttl_s = float(
            provider_config.get("list_cache_ttl_s", 5.0))
        #: (slice id, notice) pairs already reported as drain events
        self._maintenance_seen: set = set()

    # ----------------------------------------------------------- listing
    def _list_cluster_nodes(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            if self._list_cache is not None and \
                    now - self._list_cache_at < self.list_cache_ttl_s:
                return self._list_cache
        nodes = [
            n for n in self.api.list_nodes()
            if n.get("labels", {}).get(LABEL_CLUSTER) == self.cluster_name
            and n.get("state", "READY") in _LIVE_STATES]
        with self._lock:
            self._list_cache = nodes
            self._list_cache_at = now
            # a listed slice is no longer only "creating"
            listed = {n["labels"].get(LABEL_NODE_ID) for n in nodes}
            for nid in list(self._creating):
                if nid in listed:
                    del self._creating[nid]
            for n in nodes:
                nid = n["labels"].get(LABEL_NODE_ID)
                if nid and nid not in self._meta:
                    self._meta[nid] = {
                        "type": n["labels"].get(LABEL_NODE_TYPE, ""),
                        "name": n["name"]}
        return nodes

    def _invalidate(self) -> None:
        with self._lock:
            self._list_cache = None

    def non_terminated_nodes(self) -> List[str]:
        listed = [n["labels"][LABEL_NODE_ID]
                  for n in self._list_cluster_nodes()
                  if n.get("labels", {}).get(LABEL_NODE_ID)]
        with self._lock:
            pending = [nid for nid in self._creating
                       if nid not in listed]
        return listed + pending

    def node_type(self, node_id: str) -> str:
        with self._lock:
            meta = self._meta.get(node_id)
        if meta is None:
            raise KeyError(f"unknown provider node {node_id}")
        return meta["type"]

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return dict(self._resources.get(self.node_type(node_id), {}))

    # ---------------------------------------------------------- creation
    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        """Create ONE pod slice for ``node_type``. Asynchronous: returns
        as soon as the API accepts the create; the slice shows up in
        inventory immediately (pending) so demand it will absorb doesn't
        trigger duplicate launches."""
        template = self.node_configs.get(node_type)
        if template is None:
            raise KeyError(
                f"no node_config for node type {node_type!r} "
                f"(configured: {sorted(self.node_configs)})")
        node_id = f"ray-{self.cluster_name}-{node_type}-" \
                  f"{uuid.uuid4().hex[:8]}"
        body = dict(template)
        labels = dict(body.get("labels", {}))
        labels.update({LABEL_CLUSTER: self.cluster_name,
                       LABEL_NODE_TYPE: node_type,
                       LABEL_NODE_ID: node_id})
        body["labels"] = labels
        # external IPs are required for SSH (reference:
        # GCPTPU.create_instance sets networkConfig.enableExternalIps)
        net = dict(body.get("networkConfig", {}))
        net.setdefault("enableExternalIps", True)
        body["networkConfig"] = net
        script = self.provider_config.get("startup_script")
        if script:
            md = dict(body.get("metadata", {}))
            md["startup-script"] = script.format(
                head=self.provider_config.get("head_address", ""),
                node_type=node_type, node_id=node_id)
            body["metadata"] = md
        op = self.api.create_node(node_id, body)
        with self._lock:
            self._creating[node_id] = op
            self._meta[node_id] = {
                "type": node_type,
                "name": f"{self.api.parent}/nodes/{node_id}"}
        self._invalidate()
        logger.info("gce: creating TPU slice %s (%s)", node_id, node_type)
        return node_id

    def wait_until_ready(self, node_id: str,
                         timeout_s: float = 900.0) -> dict:
        """Block until the slice reaches READY (used by `ray-tpu up` for
        the head; the autoscaler never blocks here)."""
        with self._lock:
            op = self._creating.get(node_id)
            meta = self._meta.get(node_id)
        if meta is None:
            raise KeyError(f"unknown provider node {node_id}")
        if op is not None:
            self.api.wait_operation(op, timeout_s=timeout_s)
        from ray_tpu.util.backoff import ExponentialBackoff
        bo = ExponentialBackoff(base=1.0, cap=5.0, jitter="equal")
        deadline = time.monotonic() + timeout_s
        while True:
            node = self.api.get_node(meta["name"])
            if node.get("state") == "READY":
                self._invalidate()
                return node
            if node.get("state") not in _LIVE_STATES:
                raise TPUApiError(
                    f"slice {node_id} entered state {node.get('state')}")
            if time.monotonic() > deadline:
                raise TPUApiError(f"slice {node_id} not READY "
                                  f"after {timeout_s}s")
            # jittered poll through the API client's injectable sleep
            # (tests never really wait; real runs don't sync-poll)
            self.api._sleep(bo.next_delay())

    # ------------------------------------------------------- termination
    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            meta = self._meta.pop(node_id, None)
            self._creating.pop(node_id, None)
        if meta is None:
            return
        try:
            self.api.delete_node(meta["name"])
        except TPUApiError as e:
            if e.status != 404:
                raise
        self._invalidate()
        logger.info("gce: deleted TPU slice %s", node_id)

    # ---------------------------------------------------------- identity
    def internal_ids(self, node_id: str) -> List[bytes]:
        """Controller NodeIDs of every host VM in the slice (a
        v5litepod-64 slice has 16) — empty until the hosts register."""
        return list(self._resolve_internal(node_id))

    def internal_id(self, node_id: str) -> Optional[bytes]:
        ids = self.internal_ids(node_id)
        return ids[0] if ids else None

    def expected_internal_count(self, node_id: str) -> int:
        """Host-VM count of the slice, from the API's networkEndpoints
        (authoritative once the slice exists; 1 before it's listed)."""
        eps = self.host_endpoints(node_id)
        return max(1, len(eps))

    def host_endpoints(self, node_id: str) -> List[dict]:
        """The slice's host VM endpoints (ip/port) for command running."""
        for n in self._list_cluster_nodes():
            if n.get("labels", {}).get(LABEL_NODE_ID) == node_id:
                return list(n.get("networkEndpoints", []))
        return []

    # ---- slice-granular API: one provider node IS one pod slice ----
    def create_slice(self, slice_type: str, topology: str = "",
                     host_resources: Optional[Dict[str, float]] = None
                     ) -> str:
        return self.create_node(
            slice_type,
            dict(host_resources or self._resources.get(slice_type, {})))

    def delete_slice(self, slice_id: str) -> None:
        self.terminate_node(slice_id)

    def slice_hosts(self, slice_id: str) -> List[str]:
        eps = self.host_endpoints(slice_id)
        return [e.get("ipAddress") or f"{slice_id}-host{i}"
                for i, e in enumerate(eps)]

    def maintenance_events(self) -> List[dict]:
        """Upcoming-maintenance drain notices from the node listing:
        the TPU API surfaces scheduled host maintenance on the node
        body (``upcomingMaintenance``) and self-repair as the
        REPAIRING state — either one means the slice's hosts are about
        to bounce, so the SliceManager drains proactively. Each
        (slice, notice) pair is reported once; the parsed window
        fields (:func:`parse_upcoming_maintenance`) ride on the event
        so a trainer can decide how urgently to quiesce."""
        out: List[dict] = []
        for n in self._list_cluster_nodes():
            nid = n.get("labels", {}).get(LABEL_NODE_ID)
            if not nid:
                continue
            notice = n.get("upcomingMaintenance")
            if notice is None and n.get("state") == "REPAIRING":
                notice = "REPAIRING"
            if notice is None:
                continue
            key = (nid, json.dumps(notice, sort_keys=True)
                   if isinstance(notice, dict) else str(notice))
            with self._lock:
                if key in self._maintenance_seen:
                    continue
                self._maintenance_seen.add(key)
            ev = {"slice_id": nid, "kind": "maintenance",
                  "event_id": f"gce-{len(self._maintenance_seen)}"}
            if isinstance(notice, dict):
                ev.update(parse_upcoming_maintenance(notice))
            out.append(ev)
        return out


def parse_upcoming_maintenance(notice: dict) -> dict:
    """Flatten a TPU-API ``upcomingMaintenance`` body into the fields
    the drain path keys on. The API spells these camelCase
    (``windowStartTime``/``canReschedule``/...); a rename or type drift
    here would silently disable preemption notices, so the shape is
    pinned by a recorded-response fixture test. Missing fields are
    simply omitted — the event stays a valid drain notice either way.
    """
    out: dict = {}
    if notice.get("type") is not None:
        out["maintenance_type"] = str(notice["type"])
    if notice.get("maintenanceStatus") is not None:
        out["maintenance_status"] = str(notice["maintenanceStatus"])
    if notice.get("canReschedule") is not None:
        out["can_reschedule"] = bool(notice["canReschedule"])
    for src, dst in (("windowStartTime", "window_start"),
                     ("windowEndTime", "window_end"),
                     ("latestWindowStartTime", "latest_window_start")):
        if notice.get(src) is not None:
            out[dst] = str(notice[src])
    return out


def state_resolver(provider_node_label: str = LABEL_NODE_ID):
    """Default internal-id resolver: controller nodes carry a
    ``ray-tpu-node-id`` label set by the startup script's
    ``ray-tpu start --labels``; join on it via the live runtime."""
    def resolve(node_id: str) -> List[bytes]:
        import ray_tpu
        if not ray_tpu.is_initialized():
            return []
        out = []
        for n in ray_tpu.nodes():
            labels = n.get("labels") or {}
            # dead entries linger in the controller's node table (a
            # restarted host VM re-registers under a fresh NodeID) —
            # only live registrations count toward the slice's hosts
            if labels.get(provider_node_label) == node_id \
                    and n.get("alive"):
                out.append(bytes.fromhex(n["node_id"]))
        return out
    return resolve
