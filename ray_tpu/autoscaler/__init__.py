"""Autoscaler: scale node pools — and whole TPU slices — to pending
demand.

Reference: ``python/ray/autoscaler/`` (v1 StandardAutoscaler + providers).
The slice layer (``slices.py``) adds the TPU-native gang unit: atomic
multi-host slices acquired for SLICE_PACK/SLICE_SPREAD placement
groups, drained preemption-aware on maintenance events, released whole.
"""

from ray_tpu.autoscaler.arbiter import (
    ArbiterPolicy, SliceArbiter, SliceClaim)
from ray_tpu.autoscaler.autoscaler import (
    AutoscalerMonitor, NodeTypeConfig, StandardAutoscaler)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider, FakeSliceProvider, NodeProvider,
    SliceCapacityError)
from ray_tpu.autoscaler.slices import (
    SliceInfo, SliceManager, SliceTypeConfig, hosts_for_topology)
from ray_tpu.autoscaler.v2 import AutoscalerV2

__all__ = [
    "ArbiterPolicy",
    "AutoscalerMonitor",
    "AutoscalerV2",
    "FakeNodeProvider",
    "FakeSliceProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "SliceArbiter",
    "SliceCapacityError",
    "SliceClaim",
    "SliceInfo",
    "SliceManager",
    "SliceTypeConfig",
    "StandardAutoscaler",
    "hosts_for_topology",
]
