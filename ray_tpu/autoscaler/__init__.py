"""Autoscaler: scale node pools to pending demand.

Reference: ``python/ray/autoscaler/`` (v1 StandardAutoscaler + providers).
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerMonitor, NodeTypeConfig, StandardAutoscaler)
from ray_tpu.autoscaler.node_provider import FakeNodeProvider, NodeProvider
from ray_tpu.autoscaler.v2 import AutoscalerV2

__all__ = [
    "AutoscalerMonitor",
    "AutoscalerV2",
    "FakeNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "StandardAutoscaler",
]
