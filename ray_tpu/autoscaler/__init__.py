"""Autoscaler: scale node pools to pending demand.

Reference: ``python/ray/autoscaler/`` (v1 StandardAutoscaler + providers).
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerMonitor, NodeTypeConfig, StandardAutoscaler)
from ray_tpu.autoscaler.node_provider import FakeNodeProvider, NodeProvider

__all__ = [
    "AutoscalerMonitor",
    "FakeNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "StandardAutoscaler",
]
