"""Autoscaler v2: instance-lifecycle reconciliation.

Reference: ``python/ray/autoscaler/v2/`` — ``instance_manager/``
(Instance protos with a QUEUED→REQUESTED→ALLOCATED→RAY_RUNNING→
RAY_STOPPING→TERMINATED state machine behind InstanceStorage) and
``scheduler.py`` (ResourceDemandScheduler computing launch/terminate
decisions from the cluster resource state the GCS aggregates). The v1
StandardAutoscaler mutates the provider imperatively inside update();
v2 separates DESIRED state (instances + their lifecycle) from
OBSERVED state (provider + controller), and a reconciler converges
them — restartable, inspectable, and testable at each transition.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import NodeTypeConfig, _fits
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)

# Instance lifecycle (reference: instance_manager.proto Instance.Status)
QUEUED = "QUEUED"                # decided to launch; not yet requested
REQUESTED = "REQUESTED"          # provider.create_node issued
ALLOCATED = "ALLOCATED"          # provider reports the node exists
RAY_RUNNING = "RAY_RUNNING"      # node manager registered with controller
RAY_STOPPING = "RAY_STOPPING"    # drain requested
TERMINATING = "TERMINATING"      # provider.terminate_node issued
TERMINATED = "TERMINATED"

_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, TERMINATED},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {RAY_STOPPING, TERMINATING},
    RAY_STOPPING: {TERMINATING},
    TERMINATING: {TERMINATED},
    TERMINATED: set(),
}


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_node_id: Optional[str] = None
    ray_node_id: Optional[bytes] = None
    launched_at: float = field(default_factory=time.monotonic)
    updated_at: float = field(default_factory=time.monotonic)
    history: List[str] = field(default_factory=list)


class InstanceStorage:
    """In-memory instance table with transition validation (reference:
    ``instance_manager/instance_storage.py``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: Dict[str, Instance] = {}

    def add(self, node_type: str) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type)
        inst.history.append(QUEUED)
        with self._lock:
            self._instances[inst.instance_id] = inst
        return inst

    def transition(self, instance_id: str, new_status: str, **updates) -> bool:
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None:
                return False
            if new_status not in _TRANSITIONS.get(inst.status, ()):
                logger.warning("invalid transition %s: %s -> %s",
                               instance_id, inst.status, new_status)
                return False
            inst.status = new_status
            inst.updated_at = time.monotonic()
            inst.history.append(new_status)
            for k, v in updates.items():
                setattr(inst, k, v)
            return True

    def list(self, *statuses: str) -> List[Instance]:
        with self._lock:
            out = list(self._instances.values())
        if statuses:
            out = [i for i in out if i.status in statuses]
        return out

    def get(self, instance_id: str) -> Optional[Instance]:
        with self._lock:
            return self._instances.get(instance_id)


class ResourceDemandScheduler:
    """Pure function: (demand, instances, node_types) -> decisions
    (reference: ``v2/scheduler.py`` ResourceDemandScheduler)."""

    def __init__(self, node_types: Dict[str, NodeTypeConfig]):
        self.node_types = node_types

    def schedule(self, demands: List[Dict[str, float]],
                 instances: List[Instance],
                 idle_ray_nodes: List[str]) -> Dict[str, Any]:
        """Returns {"launch": {node_type: n}, "terminate": [instance_id]}."""
        active = [i for i in instances
                  if i.status in (QUEUED, REQUESTED, ALLOCATED,
                                  RAY_RUNNING)]
        # In-flight capacity absorbs demand before new launches: nodes
        # already requested/allocating will join and take queued work.
        # RAY_RUNNING nodes do NOT count — the cluster scheduler already
        # placed what fits on them; queued demand is by definition what
        # they could not hold.
        free: List[Dict[str, float]] = []
        for i in active:
            if i.status == RAY_RUNNING:
                continue
            t = self.node_types.get(i.node_type)
            if t is not None:
                free.append(dict(t.resources))
        unmet: List[Dict[str, float]] = []
        for d in demands:
            placed = False
            for cap in free:
                if _fits(cap, d):
                    for k, v in d.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unmet.append(d)

        launch: Dict[str, int] = {}
        counts: Dict[str, int] = {}
        for i in active:
            counts[i.node_type] = counts.get(i.node_type, 0) + 1
        # bin-pack unmet demand into PLANNED launches first: ten 1-CPU
        # demands fill one 8-CPU node, not ten (v1 planned_room parity)
        planned_room: List[Dict[str, float]] = []
        for d in unmet:
            placed = False
            for room in planned_room:
                if _fits(room, d):
                    for k, v in d.items():
                        room[k] = room.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            for name, t in self.node_types.items():
                total = counts.get(name, 0) + launch.get(name, 0)
                if _fits(t.resources, d) and total < t.max_workers:
                    launch[name] = launch.get(name, 0) + 1
                    room = dict(t.resources)
                    for k, v in d.items():
                        room[k] = room.get(k, 0.0) - v
                    planned_room.append(room)
                    break
        # min_workers floor
        for name, t in self.node_types.items():
            total = counts.get(name, 0) + launch.get(name, 0)
            if total < t.min_workers:
                launch[name] = launch.get(name, 0) + \
                    (t.min_workers - total)

        # idle RAY_RUNNING instances above the floor may terminate
        terminate: List[str] = []
        if not demands:
            by_type: Dict[str, List[Instance]] = {}
            for i in active:
                if i.status == RAY_RUNNING:
                    by_type.setdefault(i.node_type, []).append(i)
            idle = set(idle_ray_nodes)
            for name, insts in by_type.items():
                t = self.node_types.get(name)
                floor = t.min_workers if t else 0
                killable = [i for i in insts
                            if i.provider_node_id in idle]
                for i in killable[:max(0, len(insts) - floor)]:
                    terminate.append(i.instance_id)
        return {"launch": launch, "terminate": terminate}


class AutoscalerV2:
    """The reconciler: observe -> decide -> converge (reference:
    ``v2/autoscaler.py`` + ``instance_manager/reconciler.py``)."""

    def __init__(self, controller, provider: NodeProvider,
                 node_types: List[NodeTypeConfig],
                 idle_timeout_s: float = 60.0,
                 slice_manager=None):
        self.controller = controller
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.storage = InstanceStorage()
        self.scheduler = ResourceDemandScheduler(self.node_types)
        self.idle_timeout_s = idle_timeout_s
        self._idle_since: Dict[str, float] = {}
        #: optional slice-granular layer (autoscaler/slices.py): the
        #: reconciler hands it the same demand snapshot each pass, so
        #: unplaceable SLICE_* placement groups demand whole slices
        #: and idle slices scale down as a unit
        self.slice_manager = slice_manager

    # -------------------------------------------------------- reconcile
    def update(self) -> Dict[str, Any]:
        from ray_tpu.autoscaler.autoscaler import (
            collect_demand_snapshot, drain_nodes_if_idle)
        snap = self.controller.call_on_loop(
            lambda: collect_demand_snapshot(self.controller))
        provider_nodes = set(self.provider.non_terminated_nodes())

        # 0. adopt provider nodes we didn't launch (head-start nodes,
        # restarts of this reconciler) — slices the slice layer owns
        # stay out of the node-granular books: their lifecycle (and
        # SLICE_* flight events) belongs to the SliceManager alone
        known = {i.provider_node_id for i in self.storage.list()}
        if self.slice_manager is not None:
            known |= set(self.slice_manager.slices)
        for pid in provider_nodes - known:
            inst = self.storage.add(self.provider.node_type(pid))
            self.storage.transition(inst.instance_id, REQUESTED,
                                    provider_node_id=pid)

        # 1. sync instance states with observation
        for inst in self.storage.list(REQUESTED):
            if inst.provider_node_id in provider_nodes:
                self.storage.transition(inst.instance_id, ALLOCATED)
        for inst in self.storage.list(ALLOCATED):
            # slice-granular join: every expected host VM must be alive
            # (a multi-host TPU slice is RAY_RUNNING only when whole)
            ids = self.provider.internal_ids(inst.provider_node_id)
            if ids and len(ids) >= self.provider.expected_internal_count(
                    inst.provider_node_id) and all(
                    i in snap["alive_nodes"] for i in ids):
                self.storage.transition(inst.instance_id, RAY_RUNNING,
                                        ray_node_id=ids[0])
        for inst in self.storage.list(REQUESTED, ALLOCATED, RAY_RUNNING):
            if inst.provider_node_id is not None and \
                    inst.provider_node_id not in provider_nodes:
                # the node vanished under us: walk only the legal
                # transitions from wherever it currently is
                if inst.status == REQUESTED:
                    self.storage.transition(inst.instance_id, TERMINATED)
                elif inst.status == ALLOCATED:
                    self.storage.transition(inst.instance_id, TERMINATING)
                    self.storage.transition(inst.instance_id, TERMINATED)
                else:  # RAY_RUNNING
                    self.storage.transition(inst.instance_id, TERMINATING)
                    self.storage.transition(inst.instance_id, TERMINATED)

        # 2. idle tracking for scale-down
        now = time.monotonic()
        idle = []
        for inst in self.storage.list(RAY_RUNNING):
            pid = inst.provider_node_id
            ids = self.provider.internal_ids(pid)
            if ids and all(i in snap["alive_nodes"] for i in ids) \
                    and not any(i in snap["busy_nodes"] for i in ids) \
                    and not snap["demand"]:
                since = self._idle_since.setdefault(pid, now)
                if now - since >= self.idle_timeout_s:
                    idle.append(pid)
            else:
                self._idle_since.pop(pid, None)

        # 3. decide
        decisions = self.scheduler.schedule(
            snap["demand"], self.storage.list(), idle)

        # 4. converge
        launched = []
        for node_type, n in decisions["launch"].items():
            t = self.node_types[node_type]
            for _ in range(n):
                inst = self.storage.add(node_type)
                try:
                    pid = self.provider.create_node(node_type,
                                                    t.resources)
                except Exception:
                    logger.exception("create_node failed")
                    self.storage.transition(inst.instance_id, TERMINATED)
                    continue
                self.storage.transition(inst.instance_id, REQUESTED,
                                        provider_node_id=pid)
                launched.append(inst.instance_id)
        terminated = []
        for iid in decisions["terminate"]:
            inst = self.storage.get(iid)
            if inst is None or inst.status != RAY_RUNNING:
                continue
            # drain ALL host VMs of the slice atomically on the
            # controller loop (DrainNode before termination — same
            # race-closure as v1; one busy host vetoes the slice)
            all_ids = self.provider.internal_ids(inst.provider_node_id) \
                or ([inst.ray_node_id] if inst.ray_node_id else [])
            if all_ids and not self.controller.call_on_loop(
                    lambda ids=all_ids:
                    drain_nodes_if_idle(self.controller, ids)):
                self._idle_since.pop(inst.provider_node_id, None)
                continue
            if self.storage.transition(iid, RAY_STOPPING):
                self.storage.transition(iid, TERMINATING)
                try:
                    self.provider.terminate_node(inst.provider_node_id)
                except Exception:
                    logger.exception("terminate_node failed")
                self.storage.transition(iid, TERMINATED)
                self._idle_since.pop(inst.provider_node_id, None)
                terminated.append(iid)
        out = {"launched": launched, "terminated": terminated,
               "instances": {i.instance_id: i.status
                             for i in self.storage.list()}}
        # 5. slice-granular layer: gang demand -> whole slices
        if self.slice_manager is not None:
            out["slices"] = self.slice_manager.update(snap=snap)
        return out
