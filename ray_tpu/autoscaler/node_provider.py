"""NodeProvider plugin interface.

Reference: ``python/ray/autoscaler/node_provider.py`` (NodeProvider ABC —
create/terminate/list with tag queries, implemented per cloud) and
``python/ray/autoscaler/_private/fake_multi_node/node_provider.py`` (the
fake provider used by the reference's own autoscaler tests, which launches
real raylets on localhost). The TPU-native surface is narrower: node types
map to TPU slice hosts, and providers launch whole node managers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Minimal provider contract the autoscaler drives."""

    def __init__(self, provider_config: Dict[str, Any]):
        self.provider_config = provider_config

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_resources(self, node_id: str) -> Dict[str, float]:
        raise NotImplementedError

    def node_type(self, node_id: str) -> str:
        raise NotImplementedError

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def internal_id(self, node_id: str) -> Optional[bytes]:
        """Cluster NodeID binary for a provider node once it registered,
        None before. Lets the autoscaler join provider inventory with
        controller-side utilization."""
        raise NotImplementedError

    def internal_ids(self, node_id: str) -> List[bytes]:
        """ALL controller NodeIDs belonging to this provider node — a
        multi-host TPU slice maps one provider node to one NodeID per
        host VM. Default: the single-id contract."""
        one = self.internal_id(node_id)
        return [one] if one is not None else []

    def expected_internal_count(self, node_id: str) -> int:
        """How many cluster nodes this provider node contributes when
        fully joined (host VMs of a slice). The autoscaler treats the
        node as still starting until that many have registered."""
        return 1


class FakeNodeProvider(NodeProvider):
    """Launches REAL node-manager processes on this host (reference:
    fake_multi_node) — scaled-up nodes genuinely join the cluster and run
    tasks, so autoscaler tests exercise the true join/drain paths."""

    def __init__(self, session_dir: str,
                 provider_config: Optional[Dict[str, Any]] = None):
        super().__init__(provider_config or {})
        self.session_dir = session_dir
        self._procs: Dict[str, subprocess.Popen] = {}
        self._meta: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [nid for nid, p in self._procs.items()
                    if p.poll() is None]

    def node_resources(self, node_id: str) -> Dict[str, float]:
        with self._lock:
            return dict(self._meta[node_id]["resources"])

    def node_type(self, node_id: str) -> str:
        with self._lock:
            return self._meta[node_id]["type"]

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        node_id = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
        cluster_node_id = os.urandom(28).hex()  # NodeID is 28 bytes
        res = dict(resources)
        cpus = res.pop("CPU", 1)
        tpus = res.pop("TPU", 0)
        cmd = [sys.executable, "-m", "ray_tpu.core.node",
               "--session-dir", self.session_dir,
               "--num-cpus", str(cpus),
               "--resources", json.dumps(res),
               "--labels", json.dumps({"autoscaler-node-type": node_type}),
               "--node-id", cluster_node_id,
               "--initial-workers", "0"]
        if tpus:
            cmd += ["--num-tpus", str(tpus)]
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        env = dict(os.environ)
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in [pkg_parent, existing] if p)
        with open(os.path.join(log_dir, f"{node_id}.out"), "ab") as log:
            proc = subprocess.Popen(
                cmd, env=env, stdout=log,
                stderr=subprocess.STDOUT, start_new_session=True)
        with self._lock:
            self._procs[node_id] = proc
            self._meta[node_id] = {
                "type": node_type, "resources": resources,
                "cluster_node_id": bytes.fromhex(cluster_node_id),
                "created_at": time.time()}
        return node_id

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(node_id, None)
            self._meta.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def internal_id(self, node_id: str) -> Optional[bytes]:
        with self._lock:
            meta = self._meta.get(node_id)
            return meta["cluster_node_id"] if meta else None

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)
