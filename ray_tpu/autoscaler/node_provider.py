"""NodeProvider plugin interface.

Reference: ``python/ray/autoscaler/node_provider.py`` (NodeProvider ABC —
create/terminate/list with tag queries, implemented per cloud) and
``python/ray/autoscaler/_private/fake_multi_node/node_provider.py`` (the
fake provider used by the reference's own autoscaler tests, which launches
real raylets on localhost). The TPU-native surface is narrower: node types
map to TPU slice hosts, and providers launch whole node managers.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class SliceCapacityError(RuntimeError):
    """The provider cannot admit another slice right now (stockout,
    quota, or a configured cap): the caller keeps its demand pending
    and retries on a later reconcile pass."""


class NodeProvider:
    """Minimal provider contract the autoscaler drives."""

    def __init__(self, provider_config: Dict[str, Any]):
        self.provider_config = provider_config

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_resources(self, node_id: str) -> Dict[str, float]:
        raise NotImplementedError

    def node_type(self, node_id: str) -> str:
        raise NotImplementedError

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def internal_id(self, node_id: str) -> Optional[bytes]:
        """Cluster NodeID binary for a provider node once it registered,
        None before. Lets the autoscaler join provider inventory with
        controller-side utilization."""
        raise NotImplementedError

    def internal_ids(self, node_id: str) -> List[bytes]:
        """ALL controller NodeIDs belonging to this provider node — a
        multi-host TPU slice maps one provider node to one NodeID per
        host VM. Default: the single-id contract."""
        one = self.internal_id(node_id)
        return [one] if one is not None else []

    def expected_internal_count(self, node_id: str) -> int:
        """How many cluster nodes this provider node contributes when
        fully joined (host VMs of a slice). The autoscaler treats the
        node as still starting until that many have registered."""
        return 1

    # ---- slice-granular API: the gang unit (reference: one Cloud TPU
    # pod slice = one atomic multi-host allocation) ----
    def create_slice(self, slice_type: str, topology: str = "",
                     host_resources: Optional[Dict[str, float]] = None
                     ) -> str:
        """Atomically request a whole multi-host slice; returns its
        provider id. All host VMs come up together or the create
        raises (never a partial slice). Default contract: one provider
        node IS one slice (the gce.py/gke.py model), so the node API
        carries it. Raises :class:`SliceCapacityError` on stockout."""
        return self.create_node(slice_type, dict(host_resources or {}))

    def delete_slice(self, slice_id: str) -> None:
        """Release the whole slice — every host VM goes down as a
        unit."""
        self.terminate_node(slice_id)

    def slice_hosts(self, slice_id: str) -> List[str]:
        """Provider-level host handles (VM names / endpoints) of the
        slice, stable across calls."""
        return [slice_id]

    def maintenance_events(self) -> List[dict]:
        """Drain-pending maintenance notices:
        ``[{"slice_id", "kind", "event_id"}, ...]``. Each event is
        reported exactly once; the SliceManager answers with a
        preemption-aware drain."""
        return []


def _launch_local_node(session_dir: str, resources: Dict[str, float],
                       labels: Dict[str, str], cluster_node_id: str,
                       log_name: str) -> subprocess.Popen:
    """Start one REAL node-manager process joining ``session_dir``
    (shared by the fake single-node and slice providers — scaled-up
    nodes genuinely join the cluster and run tasks)."""
    res = dict(resources)
    cpus = res.pop("CPU", 1)
    tpus = res.pop("TPU", 0)
    cmd = [sys.executable, "-m", "ray_tpu.core.node",
           "--session-dir", session_dir,
           "--num-cpus", str(cpus),
           "--resources", json.dumps(res),
           "--labels", json.dumps(labels),
           "--node-id", cluster_node_id,
           "--initial-workers", "0"]
    if tpus:
        cmd += ["--num-tpus", str(tpus)]
    log_dir = os.path.join(session_dir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    env = dict(os.environ)
    import ray_tpu
    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [pkg_parent, existing] if p)
    with open(os.path.join(log_dir, f"{log_name}.out"), "ab") as log:
        return subprocess.Popen(
            cmd, env=env, stdout=log,
            stderr=subprocess.STDOUT, start_new_session=True)


class FakeNodeProvider(NodeProvider):
    """Launches REAL node-manager processes on this host (reference:
    fake_multi_node) — scaled-up nodes genuinely join the cluster and run
    tasks, so autoscaler tests exercise the true join/drain paths."""

    def __init__(self, session_dir: str,
                 provider_config: Optional[Dict[str, Any]] = None):
        super().__init__(provider_config or {})
        self.session_dir = session_dir
        self._procs: Dict[str, subprocess.Popen] = {}
        self._meta: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [nid for nid, p in self._procs.items()
                    if p.poll() is None]

    def node_resources(self, node_id: str) -> Dict[str, float]:
        with self._lock:
            return dict(self._meta[node_id]["resources"])

    def node_type(self, node_id: str) -> str:
        with self._lock:
            return self._meta[node_id]["type"]

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        node_id = f"fake-{node_type}-{uuid.uuid4().hex[:8]}"
        cluster_node_id = os.urandom(28).hex()  # NodeID is 28 bytes
        proc = _launch_local_node(
            self.session_dir, resources,
            {"autoscaler-node-type": node_type},
            cluster_node_id, node_id)
        with self._lock:
            self._procs[node_id] = proc
            self._meta[node_id] = {
                "type": node_type, "resources": resources,
                "cluster_node_id": bytes.fromhex(cluster_node_id),
                "created_at": time.time()}
        return node_id

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(node_id, None)
            self._meta.pop(node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def internal_id(self, node_id: str) -> Optional[bytes]:
        with self._lock:
            meta = self._meta.get(node_id)
            return meta["cluster_node_id"] if meta else None

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class FakeSliceProvider(NodeProvider):
    """Deterministic multi-host TPU-slice provider for tests and the
    local ``ray-tpu up`` round-trip.

    Two modes:

    - ``session_dir`` given: every host VM of a created slice is a
      REAL node-manager subprocess joining the session, labelled with
      the slice id (``ray-tpu-slice-id``), so gang placement, drain
      and preemption tests exercise the true join/death paths. Slice
      state persists to ``<session_dir>/fake_slices.json`` — a
      separate process (``ray-tpu down``) tears the same slices down.
    - ``session_dir=None``: in-memory hosts with synthetic NodeIDs for
      clusterless unit tests of the gang math (no processes at all).

    Creation is atomic: all host VMs launch or none (a mid-launch
    failure rolls the partial slice back). ``max_slices`` in
    ``provider_config`` caps capacity — :class:`SliceCapacityError`
    beyond it is the fake stockout that keeps a slice-spanning gang
    PENDING with no partial leases. Maintenance notices are injected
    directly (:meth:`inject_maintenance`) or scheduled
    deterministically from the chaos config (``ChaosConfig.
    maintenance``: ``{"after_s": t, "slice_index": i}`` fires ``t``
    seconds after provider creation against the i-th created slice)."""

    STATE_FILE = "fake_slices.json"

    def __init__(self, session_dir: Optional[str] = None,
                 provider_config: Optional[Dict[str, Any]] = None):
        super().__init__(provider_config or {})
        self.session_dir = session_dir
        self.max_slices = int(self.provider_config.get("max_slices", 8))
        self._lock = threading.Lock()
        #: sid -> {type, topology, hosts: [{host, cluster_node_id,
        #: pid}], index, host_resources, created_at}
        self._slices: Dict[str, dict] = {}
        self._procs: Dict[str, List[subprocess.Popen]] = {}
        #: slice ids THIS instance deleted — reload/persist merges must
        #: not resurrect them from another process's stale write
        self._deleted: set = set()
        self._created = 0
        self._t0 = time.monotonic()
        self._pending_events: List[dict] = []
        self._fired_chaos: set = set()
        self._event_seq = 0
        from ray_tpu.core.chaos import ChaosConfig
        chaos_cfg = ChaosConfig.from_env()
        self._chaos_maintenance = list(
            chaos_cfg.maintenance) if chaos_cfg else []
        if session_dir:
            self._load_state()

    # ------------------------------------------------------- persistence
    def _state_path(self) -> str:
        return os.path.join(self.session_dir, self.STATE_FILE)

    def _load_state(self) -> None:
        try:
            with open(self._state_path()) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        self._slices = data.get("slices", {})
        self._created = data.get("created", len(self._slices))

    def reload_state(self) -> None:
        """Merge slices persisted by ANOTHER process into this
        instance (the head-started SliceManager monitor and a
        ``ray-tpu up`` launcher share one state file from different
        pids): disk wins for slices whose host procs this instance
        doesn't own and didn't itself delete. Called by
        ``SliceManager.adopt_existing`` before every reconcile pass."""
        if not self.session_dir:
            return
        try:
            with open(self._state_path()) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        disk = data.get("slices", {})
        with self._lock:
            deleted = getattr(self, "_deleted", set())
            for sid, meta in disk.items():
                if sid not in self._slices and sid not in deleted:
                    self._slices[sid] = meta
            for sid in list(self._slices):
                if sid not in disk and sid not in self._procs:
                    self._slices.pop(sid)
            self._created = max(self._created,
                                int(data.get("created", 0)))

    def _persist_locked(self) -> None:
        if not self.session_dir:
            return
        tmp = self._state_path() + ".tmp"
        os.makedirs(self.session_dir, exist_ok=True)
        # merge-on-write: keep slices another process persisted (and
        # this instance neither owns nor deleted) instead of clobbering
        # them with our in-memory view
        merged = dict(self._slices)
        deleted = getattr(self, "_deleted", set())
        try:
            with open(self._state_path()) as f:
                disk = json.load(f).get("slices", {})
            for sid, meta in disk.items():
                if sid not in merged and sid not in deleted \
                        and sid not in self._procs:
                    merged[sid] = meta
        except (OSError, ValueError):
            pass
        with open(tmp, "w") as f:
            json.dump({"slices": merged,
                       "created": self._created}, f)
        os.replace(tmp, self._state_path())

    # ------------------------------------------------------------ slices
    def create_slice(self, slice_type: str, topology: str = "2x2",
                     host_resources: Optional[Dict[str, float]] = None
                     ) -> str:
        from ray_tpu.autoscaler.slices import hosts_for_topology
        n_hosts = hosts_for_topology(topology)
        host_resources = dict(host_resources or {"CPU": 1})
        with self._lock:
            if len(self._slices) >= self.max_slices:
                raise SliceCapacityError(
                    f"fake provider at capacity "
                    f"({self.max_slices} slices)")
            index = self._created
            self._created += 1
        sid = f"slice-{slice_type}-{uuid.uuid4().hex[:8]}"
        hosts: List[dict] = []
        procs: List[subprocess.Popen] = []
        try:
            for i in range(n_hosts):
                cluster_node_id = os.urandom(28).hex()
                rec = {"host": f"{sid}-host{i}",
                       "cluster_node_id": cluster_node_id, "pid": None}
                if self.session_dir:
                    proc = _launch_local_node(
                        self.session_dir, host_resources,
                        {"ray-tpu-slice-id": sid,
                         "autoscaler-node-type": slice_type},
                        cluster_node_id, rec["host"])
                    rec["pid"] = proc.pid
                    procs.append(proc)
                hosts.append(rec)
        except Exception:
            # all-or-nothing: a failed host launch rolls the slice back
            for proc in procs:
                try:
                    proc.kill()
                except Exception:
                    pass
            raise
        with self._lock:
            self._slices[sid] = {
                "type": slice_type, "topology": topology,
                "hosts": hosts, "index": index,
                "host_resources": host_resources,
                "created_at": time.time()}
            self._procs[sid] = procs
            self._persist_locked()
        return sid

    def delete_slice(self, slice_id: str) -> None:
        with self._lock:
            meta = self._slices.pop(slice_id, None)
            procs = self._procs.pop(slice_id, [])
            self._deleted.add(slice_id)
            self._persist_locked()
        if meta is None:
            return
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        known = {p.pid for p in procs}
        for rec in meta["hosts"]:
            pid = rec.get("pid")
            if pid and pid not in known:
                # launched by another process (ray-tpu up): signal by pid
                try:
                    os.kill(pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + 5.0
        for proc in procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        for rec in meta["hosts"]:
            pid = rec.get("pid")
            if pid and pid not in known:
                for _ in range(50):
                    try:
                        os.kill(pid, 0)
                    except ProcessLookupError:
                        break
                    time.sleep(0.1)
                else:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass

    def slice_hosts(self, slice_id: str) -> List[str]:
        with self._lock:
            meta = self._slices.get(slice_id)
            return [h["host"] for h in meta["hosts"]] if meta else []

    def kill_host(self, slice_id: str, host_index: int) -> int:
        """Hard-preempt ONE host VM of a slice: SIGKILL the host's
        node-manager process AND every descendant process group (the
        zygote runs in its own session, so workers would otherwise
        outlive their node manager — a real VM death takes all of
        them). Chaos helper for the 3D gang-kill leg; returns the
        node-manager pid killed."""
        with self._lock:
            meta = self._slices.get(slice_id)
            if meta is None:
                raise KeyError(f"unknown slice {slice_id}")
            pid = meta["hosts"][host_index].get("pid")
        if not pid:
            raise RuntimeError(
                f"slice {slice_id} host {host_index} has no pid "
                f"(in-memory mode?)")
        seen, stack = set(), [pid]
        while stack:
            p = stack.pop()
            if p in seen:
                continue
            seen.add(p)
            try:
                import glob
                for f in glob.glob(f"/proc/{p}/task/*/children"):
                    with open(f) as fh:
                        stack.extend(int(c) for c in fh.read().split())
            except OSError:
                pass
        own = os.getpgid(0)
        pgids = set()
        for p in seen:
            try:
                pgids.add(os.getpgid(p))
            except (ProcessLookupError, PermissionError):
                pass
        for pg in pgids - {own}:
            try:
                os.killpg(pg, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        return pid

    # ----------------------------------------------------- node contract
    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._slices)

    def node_type(self, node_id: str) -> str:
        with self._lock:
            meta = self._slices.get(node_id)
        if meta is None:
            raise KeyError(f"unknown provider slice {node_id}")
        return meta["type"]

    def node_resources(self, node_id: str) -> Dict[str, float]:
        with self._lock:
            meta = self._slices.get(node_id)
        if meta is None:
            raise KeyError(f"unknown provider slice {node_id}")
        # slice-level resources: per-host resources times host count
        return {k: v * len(meta["hosts"])
                for k, v in meta["host_resources"].items()}

    def create_node(self, node_type: str,
                    resources: Dict[str, float]) -> str:
        # the autoscaler's node-granular entry maps to a 1-host slice
        return self.create_slice(node_type, "1x1", resources)

    def terminate_node(self, node_id: str) -> None:
        self.delete_slice(node_id)

    def internal_ids(self, node_id: str) -> List[bytes]:
        with self._lock:
            meta = self._slices.get(node_id)
            if meta is None:
                return []
            return [bytes.fromhex(h["cluster_node_id"])
                    for h in meta["hosts"]]

    def internal_id(self, node_id: str) -> Optional[bytes]:
        ids = self.internal_ids(node_id)
        return ids[0] if ids else None

    def expected_internal_count(self, node_id: str) -> int:
        with self._lock:
            meta = self._slices.get(node_id)
            return len(meta["hosts"]) if meta else 1

    # ------------------------------------------------------- maintenance
    def inject_maintenance(self, slice_id: str, delay_s: float = 0.0,
                           kind: str = "maintenance") -> str:
        """Schedule a drain notice for the slice (tests / chaos
        harness); returns the event id."""
        with self._lock:
            self._event_seq += 1
            eid = f"ev-{self._event_seq}"
            self._pending_events.append({
                "slice_id": slice_id, "kind": kind, "event_id": eid,
                "due": time.monotonic() + max(0.0, delay_s)})
        return eid

    def maintenance_events(self) -> List[dict]:
        now = time.monotonic()
        out: List[dict] = []
        with self._lock:
            # chaos-scheduled notices: fire once the clock passes
            # after_s AND the indexed slice exists (a schedule against
            # a not-yet-created slice waits for it)
            by_index = {m["index"]: sid
                        for sid, m in self._slices.items()}
            for i, entry in enumerate(self._chaos_maintenance):
                if i in self._fired_chaos:
                    continue
                if now - self._t0 < float(entry.get("after_s", 0.0)):
                    continue
                sid = by_index.get(int(entry.get("slice_index", 0)))
                if sid is None:
                    continue
                self._fired_chaos.add(i)
                out.append({"slice_id": sid,
                            "kind": entry.get("kind", "maintenance"),
                            "event_id": f"chaos-{i}"})
            still = []
            for ev in self._pending_events:
                if ev["due"] <= now and ev["slice_id"] in self._slices:
                    out.append({k: ev[k] for k in
                                ("slice_id", "kind", "event_id")})
                elif ev["slice_id"] in self._slices:
                    still.append(ev)
            self._pending_events = still
        return out

    def shutdown(self) -> None:
        for sid in list(self.non_terminated_nodes()):
            self.delete_slice(sid)
