"""Rotary position embeddings (RoPE).

Two layouts are supported:
- ``"neox"`` (rotate-half): the first half of the head dim is paired with
  the second half. Used by GPT-NeoX/Llama-family models.
- ``"gptj"`` (rotate-every-two): even/odd interleaved pairs, the original
  GPT-J layout.

Tables are precomputed once (f32) and gathered per position so the op is a
pure elementwise fuse target for XLA.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def rotary_table(max_len: int, rot_dim: int, base: float = 10000.0
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute (sin, cos) tables of shape (max_len, rot_dim // 2)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, rot_dim, 2,
                                          dtype=jnp.float32) / rot_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)          # (max_len, rot_dim/2)
    return jnp.sin(freqs), jnp.cos(freqs)


def apply_rotary(x: jnp.ndarray, sin: jnp.ndarray, cos: jnp.ndarray,
                 positions: Optional[jnp.ndarray] = None,
                 layout: str = "gptj") -> jnp.ndarray:
    """Apply RoPE to ``x`` of shape (..., seq, num_heads, head_dim).

    Only the leading ``2 * sin.shape[-1]`` features of head_dim are rotated
    (GPT-J rotates ``rotary_dim=64`` of its 256-dim heads); the remainder
    passes through.

    ``positions``: optional (..., seq) int array of absolute positions
    (for packed sequences / decode steps); defaults to arange.
    """
    rot = 2 * sin.shape[-1]
    seq = x.shape[-3]
    if positions is None:
        sin_p, cos_p = sin[:seq], cos[:seq]            # (seq, rot/2)
        # broadcast over leading batch dims and the heads axis
        sin_p = sin_p[:, None, :]
        cos_p = cos_p[:, None, :]
    else:
        sin_p = jnp.take(sin, positions, axis=0)[..., :, None, :]
        cos_p = jnp.take(cos, positions, axis=0)[..., :, None, :]

    x_rot, x_pass = x[..., :rot], x[..., rot:]
    x32 = x_rot.astype(jnp.float32)

    if layout == "gptj":
        x1 = x32[..., 0::2]
        x2 = x32[..., 1::2]
        r1 = x1 * cos_p - x2 * sin_p
        r2 = x2 * cos_p + x1 * sin_p
        rotated = jnp.stack([r1, r2], axis=-1).reshape(x32.shape)
    elif layout == "neox":
        half = rot // 2
        x1 = x32[..., :half]
        x2 = x32[..., half:]
        r1 = x1 * cos_p - x2 * sin_p
        r2 = x2 * cos_p + x1 * sin_p
        rotated = jnp.concatenate([r1, r2], axis=-1)
    else:
        raise ValueError(f"unknown rotary layout: {layout!r}")

    rotated = rotated.astype(x.dtype)
    if x_pass.shape[-1] == 0:
        return rotated
    return jnp.concatenate([rotated, x_pass], axis=-1)
