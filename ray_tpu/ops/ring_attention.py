"""Ring attention: sequence-parallel causal attention over an ``sp`` axis.

The TPU-idiomatic form of ring attention (Liu et al.) / DeepSpeed-Ulysses
class sequence parallelism, which the reference lacks entirely (SURVEY.md
§2.5, §5 "Long-context"). Sequence is sharded over the ``sp`` mesh axis;
each device holds a (local_seq)-chunk of Q, K, V. K/V chunks rotate around
the ring via ``jax.lax.ppermute`` while each device streams them through a
flash-style (m, l, acc) accumulator, so no device ever materializes the
full sequence — memory is O(seq/sp_size) and the permute overlaps with
compute on the ICI torus.

Written in differentiable jnp (the per-step inner attention is
``jax.checkpoint``-ed); reverse-mode AD through ``ppermute`` yields the
reverse ring automatically.

Use inside ``shard_map`` (or under jit with explicit shardings) with the
sequence dim sharded on ``axis_name``.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


@functools.partial(jax.checkpoint, static_argnums=(6,))
def _block_step(q, kb, vb, q_off, k_off, carry, causal):
    """One ring step: attend local q against one rotating k/v block.

    q: (b, sq, h, d) local queries (f32), kb/vb: (b, sk, h, d) current
    block, q_off/k_off: global offsets of the chunks, carry: (m, l, acc).
    """
    m_prev, l_prev, acc = carry
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kb)        # (b,h,sq,sk)
    if causal:
        sq, sk = q.shape[1], kb.shape[1]
        rows = q_off + jnp.arange(sq)[:, None]
        cols = k_off + jnp.arange(sk)[None, :]
        s = jnp.where((cols <= rows)[None, None], s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1)                     # (b,h,sq)
    m_next = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - m_next[..., None])
    l_next = alpha * l_prev + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p, vb)
    acc = acc * alpha[..., None] + pv
    return m_next, l_next, acc


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str = "sp", *,
                   causal: bool = True,
                   sm_scale: Optional[float] = None) -> jnp.ndarray:
    """Sequence-parallel attention; layout (batch, local_seq, heads, dim).

    Sequence chunks are laid out contiguously by ring rank: device i holds
    global positions [i*sl, (i+1)*sl). Returns the local output chunk.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    q32 = q.astype(jnp.float32) * sm_scale
    kv = (k.astype(jnp.float32), v.astype(jnp.float32))
    q_off = my_idx * sl

    # Build the initial carry FROM q so it inherits q's varying-axes type
    # (this op may be nested under an outer shard_map that is manual over
    # dp/fsdp/etc. in addition to the ring axis — the scan carry must be
    # device-varying over every axis the per-step results vary over).
    qt = jnp.swapaxes(q32, 1, 2)                     # (b,h,sl,d)
    acc0 = qt * 0.0
    m0 = qt[..., 0] * 0.0 + _NEG_INF                 # (b,h,sl)
    l0 = qt[..., 0] * 0.0

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def ring_step(carry, step):
        m, l, acc, (kb, vb) = carry
        # Block now held arrived from rank (my_idx - step) mod size.
        src = jax.lax.rem(my_idx - step + axis_size, axis_size)
        k_off = src * sl
        m, l, acc = _block_step(q32, kb, vb, q_off, k_off,
                                (m, l, acc), causal)
        kv_next = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), (kb, vb))
        return (m, l, acc, kv_next), None

    (m, l, acc, _), _ = jax.lax.scan(
        ring_step, (m0, l0, acc0, kv), jnp.arange(axis_size))

    l = jnp.where(l == 0.0, 1.0, l)
    o = acc / l[..., None]                           # (b,h,sl,d)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)     # (b,sl,h,d)
