"""Normalization ops (f32 accumulation, XLA-fusable).

These are deliberately plain jnp: XLA fuses the reductions into neighboring
elementwise work on TPU, so a Pallas kernel buys nothing here. The contract
is numerical: statistics are always computed in float32 regardless of the
activation dtype (bf16 on TPU), matching standard large-model practice.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6,
             dtype: Optional[jnp.dtype] = None) -> jnp.ndarray:
    """RMSNorm over the last axis. ``scale`` broadcast on the last axis."""
    orig_dtype = dtype or x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * scale.astype(jnp.float32)).astype(orig_dtype)


def layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-5,
               dtype: Optional[jnp.dtype] = None) -> jnp.ndarray:
    """LayerNorm over the last axis with learned scale and bias."""
    orig_dtype = dtype or x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(orig_dtype)
