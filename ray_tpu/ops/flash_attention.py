"""Flash attention for TPU: Pallas forward kernel + chunked XLA backward.

Forward: a VMEM-blocked streaming-softmax kernel. Grid is
(batch, heads, q_blocks, k_blocks) with the k axis innermost so the
(m, l, acc) scratch accumulators persist across k blocks; matmuls hit the
MXU in bf16 with float32 accumulation (``preferred_element_type``); the
log-sum-exp is emitted so the backward pass can recompute P exactly.

Backward: Pallas dq/dk/dv kernels (default) — dk/dv accumulate in VMEM
across a q scan, dq across a k scan, both recomputing P from the saved
log-sum-exp (Dao et al., Algorithm 4). The softmax-Jacobian diagonal
``delta = rowsum(dO·O)`` is precomputed ONCE by a small fused Pallas
kernel and fed to both passes, so neither rematerializes the f32
``dO·O`` product. The earlier `lax.scan` XLA formulation remains
available (``backward="xla"``) as the numerical cross-check.

Block sizes: callers may pass explicit ``block_q``/``block_k``; leaving
them ``None`` picks chip-aware defaults (:func:`default_flash_blocks`,
keyed on ``parallel.mesh.chip_spec``), and
:func:`autotune_flash_blocks` times a small candidate grid once and
caches the winner per ``(chip, seq, head_dim)``.

Layout convention at this layer: (batch, num_heads, seq, head_dim).
Use :func:`ray_tpu.ops.attention.multihead_attention` for the (B, S, H, D)
model-side API with automatic dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is importable on CPU too (for interpret mode)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_NEG_INF = -1e30  # large-finite instead of -inf: avoids NaN from inf-inf


@dataclasses.dataclass(frozen=True)
class _Cfg:
    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    interpret: bool
    bwd: str = "pallas"   # "pallas" | "xla"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, cfg: _Cfg, offset: int):
    """``offset = sk - sq``: causality is end-aligned (query i attends keys
    0..i+offset), matching ``attention_reference``'s ``tril(k=sk-sq)`` for
    decode-style sq < sk calls."""
    ib = pl.program_id(2)          # q block index
    kb = pl.program_id(3)          # k block index (innermost)
    nk = pl.num_programs(3)
    bq, bk = cfg.block_q, cfg.block_k

    @pl.when(kb == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # Under causality, blocks strictly above the diagonal contribute nothing.
    run = (kb * bk <= ib * bq + (bq - 1) + offset) if cfg.causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                                  # (bq, d)
        k = k_ref[0, 0]                                  # (bk, d)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        s = s * cfg.sm_scale
        if cfg.causal:
            rows = ib * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(cols <= rows + offset, s, _NEG_INF)

        m_prev = m_s[...]                                # (bq, 128) lanes equal
        l_prev = l_s[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)       # (bq, 1)
        m_next = jnp.maximum(m_prev, m_cur)              # (bq, 128)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, 0:1])                  # (bq, bk) f32
        l_s[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_s[...] = m_next
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, d)
        acc_s[...] = acc_s[...] * alpha[:, 0:1] + pv

    @pl.when(kb == nk - 1)
    def _final():
        l = l_s[:, 0:1]
        # Fully-masked rows (can't happen with causal self-attn) guard:
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_s[:, 0] + jnp.log(l[:, 0])).reshape(1, bq)


def _fwd_pallas(cfg: _Cfg, q, k, v) -> Tuple[jnp.ndarray, jnp.ndarray]:
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(cfg.block_q, sq)
    bk = min(cfg.block_k, sk)
    cfg = dataclasses.replace(cfg, block_q=bq, block_k=bk)
    nq, nk = sq // bq, sk // bk
    grid = (b, h, nq, nk)

    kernel = functools.partial(_fwd_kernel, cfg=cfg, offset=sk - sq)
    compiler_params = None
    if pltpu is not None and not cfg.interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h_, i, j: (b_, h_, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max m
            pltpu.VMEM((bq, 128), jnp.float32),   # running denom l
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
        ],
        compiler_params=compiler_params,
        interpret=cfg.interpret,
    )(q, k, v)
    return out, lse[:, :, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg: _Cfg, q, k, v):
    o, _ = _fwd_pallas(cfg, q, k, v)
    return o


def _flash_fwd(cfg: _Cfg, q, k, v):
    o, lse = _fwd_pallas(cfg, q, k, v)
    return o, (q, k, v, o, lse)


def _delta_kernel(o_ref, do_ref, delta_ref, *, bq: int):
    """delta = rowsum(dO * O) in f32, blocked over q — the backward's
    softmax-Jacobian diagonal, shaped like the LSE so both ride the same
    block spec in the dq and dk/dv kernels."""
    o = o_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    delta_ref[0, 0] = jnp.sum(o * do, axis=-1).reshape(1, bq)


def _delta_pallas(cfg: _Cfg, o, do):
    b, h, sq, d = o.shape
    bq = min(cfg.block_q, sq)
    nq = sq // bq
    compiler_params = None
    if pltpu is not None and not cfg.interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel"))
    return pl.pallas_call(
        functools.partial(_delta_kernel, bq=bq),
        grid=(b, h, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 1, bq), lambda b_, h_, i: (b_, h_, 0, i)),
        out_shape=jax.ShapeDtypeStruct((b, h, 1, sq), jnp.float32),
        compiler_params=compiler_params,
        interpret=cfg.interpret,
    )(o, do)


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_s, dv_s, *, cfg: _Cfg, offset: int):
    """Grid (b, h, k_blocks, q_blocks), q innermost: dk/dv accumulators
    persist in VMEM across the q scan; P is recomputed from the saved
    LSE (the flash-attention backward recipe, Dao et al. Alg. 4)."""
    kb = pl.program_id(2)
    ib = pl.program_id(3)
    nq = pl.num_programs(3)
    bq, bk = cfg.block_q, cfg.block_k

    @pl.when(ib == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    run = (kb * bk <= ib * bq + (bq - 1) + offset) if cfg.causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                                   # (bq, d)
        k = k_ref[0, 0]                                   # (bk, d)
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)             # (bq, d)
        lse = lse_ref[0, 0]                               # (1, bq)
        delta = delta_ref[0, 0]                           # (1, bq)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.sm_scale
        if cfg.causal:
            rows = ib * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(cols <= rows + offset, s, _NEG_INF)
        p = jnp.exp(s - lse[0][:, None])                  # (bq, bk)
        # dV += P^T dO
        dv_s[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, d)
        # dS = P * (dO V^T - delta) * scale;  dK += dS^T Q
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, bk)
        ds = p * (dp - delta[0][:, None]) * cfg.sm_scale
        dk_s[...] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, d)

    @pl.when(ib == nq - 1)
    def _final():
        dk_ref[0, 0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_s, *, cfg: _Cfg, offset: int):
    """Grid (b, h, q_blocks, k_blocks), k innermost: dq accumulates in
    VMEM across the k scan."""
    ib = pl.program_id(2)
    kb = pl.program_id(3)
    nk = pl.num_programs(3)
    bq, bk = cfg.block_q, cfg.block_k

    @pl.when(kb == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    run = (kb * bk <= ib * bq + (bq - 1) + offset) if cfg.causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * cfg.sm_scale
        if cfg.causal:
            rows = ib * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(cols <= rows + offset, s, _NEG_INF)
        p = jnp.exp(s - lse[0][:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[0][:, None]) * cfg.sm_scale
        dq_s[...] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, d)

    @pl.when(kb == nk - 1)
    def _final():
        dq_ref[0, 0] = dq_s[...].astype(dq_ref.dtype)


def _bwd_pallas(cfg: _Cfg, q, k, v, o, lse, do):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(cfg.block_q, sq)
    bk = min(cfg.block_k, sk)
    cfg = dataclasses.replace(cfg, block_q=bq, block_k=bk)
    nq, nk = sq // bq, sk // bk
    offset = sk - sq
    delta = _delta_pallas(cfg, o, do)                     # (b,h,1,sq)
    lse4 = lse[:, :, None, :]                             # (b,h,1,sq)

    compiler_params = None
    if pltpu is not None and not cfg.interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, cfg=cfg, offset=offset),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h_, j, i: (b_, h_, 0, i)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h_, j, i: (b_, h_, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=cfg.interpret,
    )(q, k, v, do, lse4, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, cfg=cfg, offset=offset),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h_, i, j: (b_, h_, 0, i)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h_, i, j: (b_, h_, 0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=compiler_params,
        interpret=cfg.interpret,
    )(q, k, v, do, lse4, delta)
    return dq, dk, dv


def _flash_bwd(cfg: _Cfg, res, do):
    q, k, v, o, lse = res
    if cfg.bwd == "pallas":
        return _bwd_pallas(cfg, q, k, v, o, lse, do)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bk = min(cfg.block_k, sk)
    nk = sk // bk
    scale = cfg.sm_scale

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # D_i = sum_d dO_i * O_i — the softmax-Jacobian diagonal term.
    delta = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)     # (b,h,sq)
    rows = jnp.arange(sq)[:, None] + (sk - sq)    # end-aligned causality

    k_blocks = k.astype(jnp.float32).reshape(b, h, nk, bk, d)
    v_blocks = v.astype(jnp.float32).reshape(b, h, nk, bk, d)
    k_blocks = jnp.moveaxis(k_blocks, 2, 0)                    # (nk,b,h,bk,d)
    v_blocks = jnp.moveaxis(v_blocks, 2, 0)

    def step(dq_acc, blk):
        j, kb_, vb_ = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, kb_) * scale
        if cfg.causal:
            cols = j * bk + jnp.arange(bk)[None, :]
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                        # (b,h,sq,bk)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do32, vb_)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds, kb_)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((b, h, sq, d), jnp.float32)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        step, dq0, (jnp.arange(nk), k_blocks, v_blocks))
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, sk, d)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, sk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------- block-size selection
def default_flash_blocks(seq_q: int, seq_k: int, head_dim: int,
                         chip: Optional[str] = None) -> Tuple[int, int]:
    """Chip-aware default (block_q, block_k).

    Keyed on ``parallel.mesh.chip_spec``: wider k blocks at long sequence
    amortize the per-block softmax bookkeeping against the MXU matmuls;
    large head dims shrink both blocks to keep the f32 S/P tiles plus the
    (block, head_dim) operands inside VMEM.
    """
    if chip is None:
        try:
            from ray_tpu.parallel.mesh import chip_spec
            chip = chip_spec().name
        except Exception:  # jax backend not initializable — be safe
            chip = "cpu"
    if chip == "cpu":
        bq, bk = 256, 256
    elif head_dim >= 256:
        bq, bk = 256, 512
    elif seq_k >= 2048:
        bq, bk = 512, 1024
    else:
        bq, bk = 512, 512
    bq, bk = min(bq, seq_q), min(bk, seq_k)
    # Blocks must tile the sequence; fall back to the largest divisor.
    while seq_q % bq:
        bq //= 2
    while seq_k % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


# Winner cache: (chip, seq, head_dim, causal) -> (block_q, block_k).
_AUTOTUNE_CACHE: dict = {}

# ---- disk persistence: serving replicas must not re-time the candidate
# grid on every process start. Winners are stored as JSON keyed by
# "chip|jax_version|seq|head_dim|causal" (the jax version is part of the
# key because a compiler upgrade can move the optimum) under
# $RAY_TPU_FLASH_CACHE_DIR (default ~/.cache/ray_tpu). Only TIMED
# winners persist — chip-default fallbacks cost nothing to recompute.
_DISK_CACHE_LOADED = False


def _autotune_cache_path() -> str:
    d = os.environ.get("RAY_TPU_FLASH_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu")
    return os.path.join(d, "flash_autotune.json")


def _disk_cache_enabled() -> bool:
    return os.environ.get("RAY_TPU_FLASH_AUTOTUNE_CACHE", "1") != "0"


def _disk_key(key: tuple) -> str:
    chip, seq, head_dim, causal = key
    return f"{chip}|{jax.__version__}|{seq}|{head_dim}|{int(causal)}"


def _load_disk_cache() -> None:
    """Merge persisted winners for THIS jax version into the in-memory
    cache (once per process; misses after that re-time normally)."""
    global _DISK_CACHE_LOADED
    if _DISK_CACHE_LOADED or not _disk_cache_enabled():
        return
    _DISK_CACHE_LOADED = True
    try:
        with open(_autotune_cache_path()) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return
    ver = jax.__version__
    for k, v in data.items():
        parts = k.split("|")
        if len(parts) != 5 or parts[1] != ver:
            continue
        try:
            key = (parts[0], int(parts[2]), int(parts[3]),
                   bool(int(parts[4])))
            _AUTOTUNE_CACHE.setdefault(key, (int(v[0]), int(v[1])))
        except (TypeError, ValueError, IndexError):
            continue


def persist_cached_blocks(disk_key: str, blocks: Tuple[int, int]) -> None:
    """Write-through one timed winner under an arbitrary string key
    (read-modify-write + atomic rename; concurrent replicas may race,
    last writer wins — every intermediate state is a valid cache).
    Best-effort: a read-only filesystem must not break autotuning.
    Shared by the flash and paged autotuners — foreign key formats
    coexist in the same JSON."""
    if not _disk_cache_enabled():
        return
    path = _autotune_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[disk_key] = list(blocks)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass


def load_cached_blocks(disk_key: str) -> Optional[Tuple[int, int]]:
    """Look one persisted winner up by its exact string key (the
    generic side of the disk cache — the flash loader's bulk merge
    stays keyed on its own 5-part format)."""
    if not _disk_cache_enabled():
        return None
    try:
        with open(_autotune_cache_path()) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    v = data.get(disk_key)
    try:
        return (int(v[0]), int(v[1])) if v is not None else None
    except (TypeError, ValueError, IndexError):
        return None


def _persist_winner(key: tuple, blocks: Tuple[int, int]) -> None:
    persist_cached_blocks(_disk_key(key), blocks)

_AUTOTUNE_CANDIDATES = (
    (256, 256), (256, 512), (512, 512), (512, 1024),
    (1024, 512), (1024, 1024),
)


def _flash_block_timer(batch, heads, seq, head_dim, causal, dtype,
                       iters: int, include_backward: bool):
    """Build a timer(block_q, block_k) -> seconds for autotuning."""
    import time

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (batch, heads, seq, head_dim)
    q, k, v = (jax.random.normal(kk, shape, dtype) for kk in ks)

    def timer(bq: int, bk: int) -> float:
        def f(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=bq, block_k=bk)
            return jnp.sum(o.astype(jnp.float32))
        fn = jax.jit(jax.grad(f, argnums=(0, 1, 2))) \
            if include_backward else jax.jit(f)
        r = fn(q, k, v)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(q, k, v)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / iters

    return timer


def autotune_flash_blocks(seq: int, head_dim: int, *,
                          batch: int = 1, heads: int = 8,
                          causal: bool = True,
                          dtype=jnp.bfloat16,
                          candidates=None,
                          iters: int = 5,
                          include_backward: bool = True,
                          timer=None,
                          chip: Optional[str] = None) -> Tuple[int, int]:
    """One-shot block-size autotune: time a small candidate grid and cache
    the winner per ``(chip, seq, head_dim, causal)``.

    Off-TPU (and without an injected ``timer``) this returns the
    chip-aware default without running anything. ``timer`` is injectable
    for tests: a callable ``(block_q, block_k) -> seconds``.
    """
    if chip is None:
        try:
            from ray_tpu.parallel.mesh import chip_spec
            chip = chip_spec().name
        except Exception:
            chip = "cpu"
    key = (chip, int(seq), int(head_dim), bool(causal))
    if key in _AUTOTUNE_CACHE:
        return _AUTOTUNE_CACHE[key]
    _load_disk_cache()   # persisted winners from earlier processes
    if key in _AUTOTUNE_CACHE:
        return _AUTOTUNE_CACHE[key]

    default = default_flash_blocks(seq, seq, head_dim, chip=chip)
    cands = [c for c in (candidates or _AUTOTUNE_CANDIDATES)
             if seq % min(c[0], seq) == 0 and seq % min(c[1], seq) == 0]
    if default not in cands:
        cands.insert(0, default)
    if timer is None:
        if jax.default_backend() != "tpu" or len(cands) <= 1:
            _AUTOTUNE_CACHE[key] = default
            return default
        timer = _flash_block_timer(batch, heads, seq, head_dim, causal,
                                   dtype, iters, include_backward)
    best, best_t = default, float("inf")
    for bq, bk in cands:
        try:
            t = timer(min(bq, seq), min(bk, seq))
        except Exception:  # a candidate may not fit VMEM — skip it
            continue
        if t < best_t:
            best, best_t = (min(bq, seq), min(bk, seq)), t
    _AUTOTUNE_CACHE[key] = best
    _persist_winner(key, best)   # timed winner: survive process restarts
    return best


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False,
                    backward: str = "pallas") -> jnp.ndarray:
    """Flash attention over (batch, heads, seq, head_dim) arrays.

    Requires seq divisible by the (clamped) block sizes; ``block_q`` /
    ``block_k`` left as ``None`` (or 0) pick chip-aware defaults
    (:func:`default_flash_blocks`). ``interpret=True`` runs the Pallas
    kernels in interpreter mode (CPU tests). ``backward`` selects the VJP
    implementation: "pallas" (VMEM-blocked dq/dk/dv kernels recomputing P
    from the saved LSE) or "xla" (the lax.scan formulation, kept for
    parity checks).
    """
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if backward not in ("pallas", "xla"):
        raise ValueError(f"backward must be 'pallas' or 'xla', "
                         f"got {backward!r}")
    if not block_q or not block_k:
        dq_, dk_ = default_flash_blocks(q.shape[2], k.shape[2], d,
                                        chip="cpu" if interpret else None)
        block_q = block_q or dq_
        block_k = block_k or dk_
    cfg = _Cfg(causal=causal, sm_scale=float(sm_scale),
               block_q=block_q, block_k=block_k, interpret=interpret,
               bwd=backward)
    return _flash(cfg, q, k, v)
