"""Pallas paged-attention decode kernel — the length-aware serving
fast path.

The serving hot loop attends a handful of new-token queries per
sequence against a paged KV cache (``[num_blocks, block_size, kv_heads,
head_dim]`` pool + per-sequence block tables). The pure-XLA reference
(:func:`ray_tpu.ops.attention.paged_attention`) gathers the WHOLE
table window every step — work is O(B · T · block_size) regardless of
how many tokens a sequence actually holds. This kernel makes decode
work proportional to **live tokens**:

- grid ``(batch, kv_head_group, q_row_blocks, table_slots)`` with the
  table-slot axis innermost so the online-softmax accumulators
  (m, l, acc in f32 VMEM scratch) persist across a sequence's pages;
- the block table and per-sequence ``lens`` ride **scalar prefetch**
  (:class:`pltpu.PrefetchScalarGridSpec`): the k/v BlockSpec index
  maps read the table to DMA exactly the physical page a grid step
  needs;
- table slots past ``ceil(lens[b] / block_size)`` are **skipped** —
  their index map clamps to the last live page (an unchanged block
  index issues no new copy) and ``pl.when`` skips the matmuls, so a
  16-token sequence in a 1024-token window does 1/64th of the window's
  work instead of all of it;
- GQA is handled by **indexing kv heads in-kernel**: queries are
  regrouped host-side to ``[B, kv_heads, C·group, D]`` rows (a
  transpose of the tiny q tensor, not of the cache) and each grid step
  loads ONE kv head's page — the cache is never repeated or copied.

Rows are padded to ``block_r`` (chip-aware default via
:func:`default_paged_block_r`; :func:`autotune_paged_block_r` times a
candidate grid once and persists the winner through the SAME on-disk
cache as ``autotune_flash_blocks``). Padded rows carry position −1 —
fully masked, dropped on unpack.

``interpret=True`` runs the kernel on CPU (tier-1 parity tests); on
TPU it compiles with parallel/arbitrary dimension semantics like the
flash kernels.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is importable on CPU too (for interpret mode)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ray_tpu.ops.flash_attention import (
    load_cached_blocks, persist_cached_blocks)

_NEG_INF = -1e30


def paged_work_pages(lens, block_size: int):
    """Pages a length-aware kernel touches per sequence:
    ``max(ceil(lens / block_size), 1)`` (an idle ``lens = 0`` slot still
    runs its one trash page so the batch shape stays fixed). Works on
    numpy and jax arrays — the engine's FLOP accounting and the bench's
    work-reduction math share this definition with the kernel."""
    return ((lens + block_size - 1) // block_size).clip(min=1) \
        if hasattr(lens, "clip") else max(-(-lens // block_size), 1)


def _paged_kernel(bt_ref, lens_ref, q_ref, pos_ref, k_ref, v_ref, o_ref,
                  m_s, l_s, acc_s, *, bs: int, sm_scale: float):
    """One (batch b, kv head g, row block r, table slot t) step: fold
    page t of sequence b into the row block's online softmax. Scalar
    refs (bt, lens) land in SMEM ahead of the body — the same values
    the index maps used to pick this step's page."""
    b = pl.program_id(0)
    t = pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # Length-aware skipping: slots past the live pages do nothing (and
    # their k/v index maps re-point at the last live page, so no DMA).
    pages = jnp.maximum(pl.cdiv(lens_ref[b], bs), 1)

    @pl.when(t < pages)
    def _compute():
        q = q_ref[0, 0]                        # (block_r, d)
        k = k_ref[0, :, 0, :]                  # (bs, d) — one page, one
        v = v_ref[0, :, 0, :]                  # kv head, indexed in-kernel
        rows_pos = pos_ref[0]                  # (block_r,) int32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        key_pos = t * bs + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(key_pos <= rows_pos[:, None], s, _NEG_INF)

        m_prev = m_s[...]                      # (block_r, 128) lanes equal
        l_prev = l_s[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, 0:1])
        l_s[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_s[...] = m_next
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_s[...] = acc_s[...] * alpha[:, 0:1] + pv

    @pl.when(t == nt - 1)
    def _final():
        l = l_s[:, 0:1]
        # padded (position −1) rows never scored a key: emit zeros
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / l).astype(o_ref.dtype)


def paged_flash_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                          v_cache: jnp.ndarray,
                          block_tables: jnp.ndarray,
                          q_positions: jnp.ndarray,
                          lens: jnp.ndarray, *,
                          sm_scale: Optional[float] = None,
                          block_r: Optional[int] = None,
                          interpret: bool = False) -> jnp.ndarray:
    """Paged attention of new-token queries against the block pool.

    Same contract as the XLA reference
    (:func:`ray_tpu.ops.attention.paged_attention`): ``q`` is
    ``[B, C, H, D]`` at absolute ``q_positions [B, C]``, caches are
    ``[N, bs, KVH, D]``, ``block_tables [B, T]``. ``lens [B]`` is the
    number of LIVE cached positions per sequence (after this step's
    writes); table slots past ``ceil(lens/bs)`` are skipped entirely.
    Rows whose position ≥ ``lens[b]`` (padded prefill tail) attend only
    live keys — their outputs are the caller's to discard, exactly as
    with the reference path.
    """
    b, c, h, d = q.shape
    n_blocks, bs, g, _ = k_cache.shape
    t = block_tables.shape[1]
    if h % g:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {g}")
    rep = h // g
    rows = c * rep
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if not block_r:
        block_r = default_paged_block_r(
            rows, d, chip="cpu" if interpret else None)
    block_r = max(8, min(block_r, _round8(rows)))
    rows_pad = -(-rows // block_r) * block_r
    nr = rows_pad // block_r

    # Group-major query rows: row r of kv head g is (c = r // rep,
    # head = g*rep + r % rep). Only q (tiny) is reshaped — never the
    # cache.
    qg = q.reshape(b, c, g, rep, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b, g, rows, d)
    pos_rows = jnp.repeat(q_positions.astype(jnp.int32), rep, axis=1)
    if rows_pad != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows_pad - rows), (0, 0)))
        pos_rows = jnp.pad(pos_rows, ((0, 0), (0, rows_pad - rows)),
                           constant_values=-1)

    def _pages(ln):
        return jnp.maximum(pl.cdiv(ln, bs), 1)

    def q_map(b_, g_, r_, t_, bt, ln):
        return (b_, g_, r_, 0)

    def pos_map(b_, g_, r_, t_, bt, ln):
        return (b_, r_)

    def kv_map(b_, g_, r_, t_, bt, ln):
        # slots past the live pages revisit the last live page: the
        # unchanged block index issues no fresh DMA
        tt = jnp.minimum(t_, _pages(ln[b_]) - 1)
        return (bt[b_, tt], 0, g_, 0)

    grid = (b, g, nr, t)
    compiler_params = None
    if pltpu is not None and not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_r, d), q_map),
            pl.BlockSpec((1, block_r), pos_map),
            pl.BlockSpec((1, bs, 1, d), kv_map),
            pl.BlockSpec((1, bs, 1, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block_r, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_r, 128), jnp.float32),   # running max m
            pltpu.VMEM((block_r, 128), jnp.float32),   # running denom l
            pltpu.VMEM((block_r, d), jnp.float32),     # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, bs=bs, sm_scale=float(sm_scale)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, rows_pad, d), q.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lens.astype(jnp.int32),
      qg, pos_rows, k_cache, v_cache)
    out = out[:, :, :rows, :].reshape(b, g, c, rep, d) \
        .transpose(0, 2, 1, 3, 4).reshape(b, c, h, d)
    return out


# --------------------------------------------------- block-size selection
def _round8(n: int) -> int:
    return max(8, -(-n // 8) * 8)


def default_paged_block_r(rows: int, head_dim: int,
                          chip: Optional[str] = None) -> int:
    """Chip-aware default query-row block for the paged kernel.

    Rows = C·(heads per kv head) — tiny for batched decode (one token
    per sequence), up to a few hundred for chunked prefill. Small on
    CPU interpret (grid overhead dominates), wider on TPU so the
    row-block matmuls fill MXU tiles; large head dims halve the block
    to keep the f32 (rows, bs) score tile + accumulators in VMEM.
    """
    if chip is None:
        try:
            from ray_tpu.parallel.mesh import chip_spec
            chip = chip_spec().name
        except Exception:  # jax backend not initializable — be safe
            chip = "cpu"
    cap = 128 if chip == "cpu" else (128 if head_dim >= 256 else 256)
    return min(_round8(rows), cap)


# Winner cache: (chip, block_size, table_len, rows, head_dim) -> block_r.
_PAGED_AUTOTUNE_CACHE: dict = {}

_PAGED_CANDIDATES = (8, 16, 32, 64, 128, 256, 512)


def _paged_disk_key(key: tuple) -> str:
    chip, bs, t, rows, head_dim = key
    return f"paged|{chip}|{jax.__version__}|{bs}|{t}|{rows}|{head_dim}"


def autotune_paged_block_r(block_size: int, table_len: int, rows: int,
                           head_dim: int, *,
                           batch: int = 8,
                           dtype=jnp.bfloat16,
                           candidates=None,
                           iters: int = 5,
                           timer=None,
                           chip: Optional[str] = None) -> int:
    """One-shot row-block autotune for the paged kernel: time a small
    candidate grid once and cache the winner per
    ``(chip, block_size, table_len, rows, head_dim)``; timed winners
    persist through the SAME on-disk JSON as the flash autotuner
    (``$RAY_TPU_FLASH_CACHE_DIR/flash_autotune.json``, keys prefixed
    ``paged|``), so serving replicas never re-time on process start.

    Off-TPU (without an injected ``timer``) returns the chip-aware
    default without running anything. ``timer`` is injectable for
    tests: a callable ``(block_r) -> seconds``.
    """
    if chip is None:
        try:
            from ray_tpu.parallel.mesh import chip_spec
            chip = chip_spec().name
        except Exception:
            chip = "cpu"
    key = (chip, int(block_size), int(table_len), int(rows),
           int(head_dim))
    if key in _PAGED_AUTOTUNE_CACHE:
        return _PAGED_AUTOTUNE_CACHE[key]
    persisted = load_cached_blocks(_paged_disk_key(key))
    if persisted is not None:
        _PAGED_AUTOTUNE_CACHE[key] = int(persisted[0])
        return _PAGED_AUTOTUNE_CACHE[key]

    default = default_paged_block_r(rows, head_dim, chip=chip)
    cands = sorted({min(c, _round8(rows))
                    for c in (candidates or _PAGED_CANDIDATES)})
    if default not in cands:
        cands.insert(0, default)
    if timer is None:
        if jax.default_backend() != "tpu" or len(cands) <= 1:
            _PAGED_AUTOTUNE_CACHE[key] = default
            return default
        timer = _paged_block_timer(batch, block_size, table_len, rows,
                                   head_dim, dtype, iters)
    best, best_t = default, float("inf")
    for br in cands:
        try:
            tt = timer(br)
        except Exception:  # a candidate may not fit VMEM — skip it
            continue
        if tt < best_t:
            best, best_t = br, tt
    _PAGED_AUTOTUNE_CACHE[key] = best
    persist_cached_blocks(_paged_disk_key(key), (best, best))
    return best


def _paged_block_timer(batch, block_size, table_len, rows, head_dim,
                       dtype, iters: int):
    """Build a timer(block_r) -> seconds over a synthetic full-length
    paged batch (the worst-case decode shape)."""
    import time

    n_blocks = 1 + batch * table_len
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    kc = jax.random.normal(ks[0], (n_blocks, block_size, 1, head_dim),
                           dtype)
    vc = jax.random.normal(ks[1], (n_blocks, block_size, 1, head_dim),
                           dtype)
    q = jax.random.normal(ks[2], (batch, rows, 1, head_dim), dtype)
    bt = jnp.arange(1, n_blocks, dtype=jnp.int32).reshape(
        batch, table_len)
    lens = jnp.full((batch,), table_len * block_size, jnp.int32)
    pos = jnp.full((batch, rows), table_len * block_size - 1, jnp.int32)

    def timer(block_r: int) -> float:
        fn = jax.jit(functools.partial(
            paged_flash_attention, block_r=block_r))
        r = fn(q, kc, vc, bt, pos, lens)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(q, kc, vc, bt, pos, lens)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / iters

    return timer
