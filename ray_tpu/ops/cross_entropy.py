"""Stable softmax cross-entropy for language-model heads.

Two paths:

- :func:`cross_entropy_loss` — the reference: takes materialized logits,
  computed in float32 with log-sum-exp, optional z-loss (stabilizes the
  softmax normalizer at scale, as in PaLM), and a validity mask for
  padded / shifted-label positions.

- :func:`fused_lm_head_loss` — the memory-lean production path: takes the
  final *hidden states* and the LM-head weights and computes the loss in
  sequence chunks under a ``custom_vjp``. Per chunk it projects to logits
  (float32 MXU accumulation), reduces to log-sum-exp + label logit, and
  keeps only the per-token LSE as a residual; the backward recomputes each
  chunk's logits and softmax to form dX/dW/db. The full
  ``[batch, seq, vocab]`` float32 logits tensor is never resident — peak
  loss memory drops from ``O(b·s·v)`` to ``O(b·chunk·v)``, which is what
  frees HBM for larger batches at long sequence lengths.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None,
                       z_loss_coeff: float = 0.0,
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean token cross entropy.

    logits: (..., vocab), labels: (...) int, mask: (...) bool/float of
    valid positions. Returns (loss, n_valid_tokens) — callers doing
    data-parallel mean should psum both and divide (exact global mean).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - label_logit
    if z_loss_coeff:
        nll = nll + z_loss_coeff * jnp.square(lse)
    if mask is None:
        n = jnp.array(nll.size, jnp.float32)
        return jnp.sum(nll) / n, n
    mask = mask.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(nll * mask) / n, n


# ------------------------------------------------------- fused chunked CE
def _chunk_layout(x, labels, mask, chunk: int):
    """Pad seq to a chunk multiple and reshape to chunk-major scan inputs.

    x: (b, s, e) -> (nc, b, C, e); labels/mask: (b, s) -> (nc, b, C).
    Padded positions carry mask 0 so they contribute nothing.
    """
    b, s, e = x.shape
    c = min(chunk, s)
    nc = -(-s // c)
    pad = nc * c - s
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    xc = jnp.moveaxis(x.reshape(b, nc, c, e), 1, 0)
    yc = jnp.moveaxis(labels.reshape(b, nc, c), 1, 0)
    mc = jnp.moveaxis(mask.reshape(b, nc, c), 1, 0)
    return xc, yc, mc, pad


def _chunk_logits(xi, w, bias):
    """One chunk's logits in float32: (b, C, e) @ (e, v) + (v,)."""
    logits = jnp.einsum("bce,ev->bcv", xi, w,
                        preferred_element_type=jnp.float32)
    return logits + bias


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _fused_ce(cfg, x, w, bias, labels, mask):
    loss, n, _ = _fused_ce_fwd_impl(cfg, x, w, bias, labels, mask)
    return loss, n


def _fused_ce_fwd_impl(cfg, x, w, bias, labels, mask):
    chunk, z = cfg
    wd = w.astype(x.dtype)
    xc, yc, mc, _ = _chunk_layout(x, labels, mask, chunk)

    def body(carry, inp):
        loss_sum, n = carry
        xi, yi, mi = inp
        logits = _chunk_logits(xi, wd, bias)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, yi[..., None], axis=-1)[..., 0]
        nll = lse - ll
        if z:
            nll = nll + z * jnp.square(lse)
        return (loss_sum + jnp.sum(nll * mi), n + jnp.sum(mi)), lse

    (loss_sum, n), lses = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xc, yc, mc))
    n = jnp.maximum(n, 1.0)
    return loss_sum / n, n, lses


def _fused_ce_fwd(cfg, x, w, bias, labels, mask):
    loss, n, lses = _fused_ce_fwd_impl(cfg, x, w, bias, labels, mask)
    return (loss, n), (x, w, bias, labels, mask, lses, loss, n)


def _fused_ce_bwd(cfg, res, cts):
    chunk, z = cfg
    x, w, bias, labels, mask, lses, loss, n = res
    g_loss, _ = cts                      # n is a count — no useful cotangent
    wd = w.astype(x.dtype)
    xc, yc, mc, pad = _chunk_layout(x, labels, mask, chunk)
    b, s, e = x.shape
    v = w.shape[-1]

    def body(carry, inp):
        dw, db = carry
        xi, yi, mi, lsei = inp
        logits = _chunk_logits(xi, wd, bias)
        p = jnp.exp(logits - lsei[..., None])
        coef = (g_loss / n) * mi                       # (b, C)
        zf = (1.0 + 2.0 * z * lsei) if z else 1.0
        one_hot = jax.nn.one_hot(yi, v, dtype=jnp.float32)
        dl = p * (coef * zf)[..., None] - coef[..., None] * one_hot
        db = db + jnp.sum(dl, axis=(0, 1))
        dlc = dl.astype(x.dtype)
        dxi = jnp.einsum("bcv,ev->bce", dlc, wd,
                         preferred_element_type=jnp.float32).astype(x.dtype)
        dw = dw + jnp.einsum("bce,bcv->ev", xi, dlc,
                             preferred_element_type=jnp.float32)
        # d loss / d mask_i = (nll_i - loss) / n  (mask enters sum and n)
        ll = jnp.take_along_axis(logits, yi[..., None], axis=-1)[..., 0]
        nll = lsei - ll
        if z:
            nll = nll + z * jnp.square(lsei)
        dmi = g_loss * (nll - loss) / n
        return (dw, db), (dxi, dmi)

    (dw, db), (dxc, dmc) = jax.lax.scan(
        body,
        (jnp.zeros((e, v), jnp.float32), jnp.zeros((v,), jnp.float32)),
        (xc, yc, mc, lses))
    dx = jnp.moveaxis(dxc, 0, 1).reshape(b, -1, e)[:, :s]
    dm = jnp.moveaxis(dmc, 0, 1).reshape(b, -1)[:, :s]
    dlabels = np.zeros(labels.shape, jax.dtypes.float0)
    return dx, dw.astype(w.dtype), db.astype(bias.dtype), dlabels, \
        dm.astype(mask.dtype)


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_lm_head_loss(x: jnp.ndarray, head_w: jnp.ndarray,
                       labels: jnp.ndarray, *,
                       head_bias: Optional[jnp.ndarray] = None,
                       mask: Optional[jnp.ndarray] = None,
                       z_loss_coeff: float = 0.0,
                       chunk_size: int = 512,
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chunked fused LM-head projection + cross entropy.

    x: (b, s, e) final hidden states (compute dtype); head_w: (e, v)
    master weights (cast to ``x.dtype`` for the MXU matmul, float32
    accumulation); labels: (b, s) int; mask: (b, s) valid positions.
    ``chunk_size`` tokens of each sequence are projected at a time
    (``0``/``>= s`` degenerates to one chunk — still fused, no separate
    logits tensor or float32 upcast copy). ``z_loss_coeff`` must be a
    static Python float. Returns (mean_loss, n_valid_tokens) like
    :func:`cross_entropy_loss`.
    """
    b, s, _ = x.shape
    if mask is None:
        mask = jnp.ones((b, s), jnp.float32)
    mask = mask.astype(jnp.float32)
    bias = head_bias if head_bias is not None \
        else jnp.zeros((head_w.shape[-1],), jnp.float32)
    chunk = chunk_size if chunk_size and chunk_size > 0 else s
    cfg = (int(chunk), float(z_loss_coeff))
    return _fused_ce(cfg, x, head_w, bias, labels, mask)
