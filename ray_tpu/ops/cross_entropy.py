"""Stable softmax cross-entropy for language-model heads.

Computed from logits in float32 with log-sum-exp, optional z-loss
(stabilizes the softmax normalizer at scale, as in PaLM), and a validity
mask for padded / shifted-label positions. XLA fuses the reduction with
the projection that produced the logits, so no Pallas needed here; vocab
chunking (for very large vocabs) can be layered on later without changing
the signature.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None,
                       z_loss_coeff: float = 0.0,
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean token cross entropy.

    logits: (..., vocab), labels: (...) int, mask: (...) bool/float of
    valid positions. Returns (loss, n_valid_tokens) — callers doing
    data-parallel mean should psum both and divide (exact global mean).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - label_logit
    if z_loss_coeff:
        nll = nll + z_loss_coeff * jnp.square(lse)
    if mask is None:
        n = jnp.array(nll.size, jnp.float32)
        return jnp.sum(nll) / n, n
    mask = mask.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(nll * mask) / n, n
