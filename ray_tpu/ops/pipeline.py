"""Pipeline parallelism as a collective GSPMD program.

TPU-first design: instead of the reference's per-stage process groups and
point-to-point sends (torch pipelining would map poorly to XLA), the
pipeline IS one jitted SPMD program over the ``pp`` mesh axis:

- layer parameters are stacked ``[n_stages, ...]`` and sharded on ``pp``
  (each device holds its stage's weights, nothing else);
- a ``lax.scan`` over ticks runs the classic GPipe schedule: at tick t,
  stage s computes microbatch ``t - s``; activations hop to the next
  stage with a single ``ppermute`` per tick (one ICI neighbor hop);
- reverse-mode AD through scan+ppermute yields the backward pipeline
  schedule automatically — no hand-written 1F1B state machine.

Bubble fraction is the GPipe ``(S-1)/(M+S-1)``; choose microbatches >>
stages. The scaling-book calls this the "collective pipelining" recipe.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(layer_params_list):
    """Stack per-stage parameter pytrees into ``[n_stages, ...]`` leaves
    (shard the leading axis on ``pp``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params_list)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params, x: jax.Array, mesh: Mesh,
                   n_microbatches: int, axis: str = "pp") -> jax.Array:
    """Run ``stage_fn`` as an ``S``-stage GPipe pipeline over ``axis``.

    stage_params: pytree with leading dim S (sharded on ``axis``).
    x: ``[batch, ...]`` global input; split into ``n_microbatches``.
    Returns ``[batch, ...]`` outputs (replicated over ``axis``).
    """
    S = mesh.shape[axis]
    M = n_microbatches
    if x.shape[0] % M:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"{M} microbatches")
    xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])

    # one device's view: params [1, ...] -> squeeze; xs/out replicated
    def spmd(params, xs):
        params = jax.tree.map(lambda a: a[0], params)
        s = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            h, ys = carry
            m = t - s  # microbatch this stage works on at this tick
            # stage 0 consumes fresh input; later stages, the hopped
            # activation. Out-of-range ticks compute garbage that is
            # masked out of ys (uniform compute keeps the program static)
            x_t = xs[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(s == 0, x_t, h)
            out = stage_fn(params, inp)
            live = (m >= 0) & (m < M)
            write = live & (s == S - 1)
            idx = jnp.clip(m, 0, M - 1)
            ys = ys.at[idx].set(jnp.where(write, out, ys[idx]))
            h_next = jax.lax.ppermute(out, axis, perm)
            return (h_next, ys), None

        h0 = jnp.zeros(mb_shape, xs.dtype)
        ys0 = jnp.zeros_like(xs)
        (_, ys), _ = jax.lax.scan(tick, (h0, ys0),
                                  jnp.arange(M + S - 1))
        # only the last stage wrote real outputs; give them to everyone
        ys = jax.lax.psum(jnp.where(s == S - 1, ys, jnp.zeros_like(ys)),
                          axis)
        return ys

    pspec_params = jax.tree.map(lambda _: P(axis), stage_params)
    rep = P()
    from ray_tpu.util.jax_compat import shard_map
    out = shard_map(
        spmd, mesh=mesh,
        in_specs=(pspec_params, rep),
        out_specs=rep,
        check_vma=False,
    )(stage_params, xs)
    return out.reshape(x.shape[0:1] + out.shape[2:])
