"""TPU-native compute ops: Pallas kernels + XLA-friendly primitives.

This package holds the hot-op layer of the framework. The reference has no
equivalent (its math lives in torch/CUDA inside user code and integrations);
here attention, normalization, rotary embeddings and losses are provided as
first-class jittable ops so the model family and the libraries above share
one tuned implementation.

- ``flash_attention``: Pallas TPU kernel (VMEM-blocked, MXU matmuls,
  log-sum-exp streaming softmax), with a pure-XLA fallback for CPU tests.
- ``ring_attention``: sequence-parallel attention over an ``sp`` mesh axis
  via ``shard_map`` + ``ppermute`` (the TPU-idiomatic ring attention;
  SURVEY.md §2.5 — absent in the reference).
- ``rms_norm`` / ``layer_norm``, ``apply_rotary``, ``cross_entropy_loss``.
"""

from ray_tpu.ops.norms import rms_norm, layer_norm
from ray_tpu.ops.rotary import rotary_table, apply_rotary
from ray_tpu.ops.attention import (
    multihead_attention, attention_reference, paged_attention)
from ray_tpu.ops.flash_attention import (
    flash_attention, default_flash_blocks, autotune_flash_blocks)
from ray_tpu.ops.paged_flash import (
    paged_flash_attention, default_paged_block_r, autotune_paged_block_r,
    paged_work_pages)
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.cross_entropy import cross_entropy_loss, fused_lm_head_loss

__all__ = [
    "rms_norm",
    "layer_norm",
    "rotary_table",
    "apply_rotary",
    "multihead_attention",
    "attention_reference",
    "paged_attention",
    "flash_attention",
    "default_flash_blocks",
    "autotune_flash_blocks",
    "paged_flash_attention",
    "default_paged_block_r",
    "autotune_paged_block_r",
    "paged_work_pages",
    "ring_attention",
    "cross_entropy_loss",
    "fused_lm_head_loss",
]
