"""Model-facing attention API with automatic kernel dispatch.

Layout here is (batch, seq, num_heads, head_dim) — the layout models carry
activations in. Dispatch: the Pallas flash kernel on TPU when shapes tile
cleanly onto the MXU (head_dim % 128 == 0, seq divisible by the block);
otherwise the pure-XLA reference path (which is what CPU tests exercise).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import (
    default_flash_blocks, flash_attention)

_NEG_INF = -1e30


def attention_reference(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                        causal: bool = False,
                        sm_scale: Optional[float] = None,
                        mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain masked-softmax attention in f32, layout (B, S, H, D)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(causal_mask[None, None], s, _NEG_INF)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


def _can_use_flash(q, k, block_q: int, block_k: int) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if d % 128 != 0:
        return False
    bq, bk = min(block_q, sq), min(block_k, sk)
    return sq % bq == 0 and sk % bk == 0


def multihead_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                        causal: bool = False,
                        sm_scale: Optional[float] = None,
                        mask: Optional[jnp.ndarray] = None,
                        impl: str = "auto",
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None,
                        interpret: bool = False) -> jnp.ndarray:
    """Attention over (batch, seq, heads, head_dim).

    ``impl``: "auto" | "flash" | "reference". Arbitrary ``mask`` forces the
    reference path (the flash kernel handles only the causal structure).
    ``block_q``/``block_k`` of ``None`` (or 0) resolve to chip-aware
    defaults (``flash_attention.default_flash_blocks``).
    """
    if not block_q or not block_k:
        dq_, dk_ = default_flash_blocks(
            q.shape[1], k.shape[1], q.shape[-1],
            chip="cpu" if interpret else None)
        block_q = block_q or dq_
        block_k = block_k or dk_
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        use_flash = (mask is None and (on_tpu or interpret)
                     and _can_use_flash(q, k, block_q, block_k))
        impl = "flash" if use_flash else "reference"
    if impl == "reference" or mask is not None:
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                                   mask=mask)
    if impl != "flash":
        raise ValueError(f"unknown attention impl: {impl!r}")
    qt = jnp.swapaxes(q, 1, 2)    # (B, H, S, D)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = flash_attention(qt, kt, vt, causal=causal, sm_scale=sm_scale,
                        block_q=block_q, block_k=block_k,
                        interpret=interpret)
    return jnp.swapaxes(o, 1, 2)
