"""Model-facing attention API with automatic kernel dispatch.

Layout here is (batch, seq, num_heads, head_dim) — the layout models carry
activations in. Dispatch: the Pallas flash kernel on TPU when shapes tile
cleanly onto the MXU (head_dim % 128 == 0, seq divisible by the block);
otherwise the pure-XLA reference path (which is what CPU tests exercise).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import (
    default_flash_blocks, flash_attention)

_NEG_INF = -1e30


def attention_reference(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                        causal: bool = False,
                        sm_scale: Optional[float] = None,
                        mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain masked-softmax attention in f32, layout (B, S, H, D)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(causal_mask[None, None], s, _NEG_INF)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


def _can_use_paged_kernel(q: jnp.ndarray, k_cache: jnp.ndarray) -> bool:
    """TPU dispatch guard for the Pallas paged kernel: head_dim must
    tile the lanes; tiny KV blocks fall back (per-page matmuls would be
    bookkeeping-bound)."""
    d = q.shape[-1]
    bs = k_cache.shape[1]
    return d % 128 == 0 and bs % 8 == 0


def paged_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                    v_cache: jnp.ndarray, block_tables: jnp.ndarray,
                    q_positions: jnp.ndarray, *,
                    lens: Optional[jnp.ndarray] = None,
                    sm_scale: Optional[float] = None,
                    impl: str = "auto",
                    block_r: Optional[int] = None,
                    interpret: bool = False) -> jnp.ndarray:
    """Attention of new-token queries against a paged KV cache.

    The serving decode/prefill primitive: keys and values live in a pool
    of fixed-size blocks (``k_cache``/``v_cache`` of shape
    ``[num_blocks, block_size, kv_heads, head_dim]``); each sequence owns
    an ordered list of block ids (``block_tables[b, t]`` holds the block
    storing absolute positions ``t*block_size .. t*block_size+bs-1`` of
    sequence ``b``). Queries ``q[b, i]`` sit at absolute position
    ``q_positions[b, i]`` and attend every cached position ``<= q_positions
    [b, i]`` — causal by construction, so the SAME call serves batched
    single-token decode (``q`` of shape ``[B, 1, H, D]``) and chunked
    prefill (``[B, C, H, D]``, the chunk's own keys having been written to
    the cache first). GQA caches store ``kv_heads < num_heads``; queries
    are grouped onto their kv head at read time — the cache is never
    repeated.

    ``impl``: "auto" | "kernel" | "reference". "kernel" is the Pallas
    paged kernel (:mod:`ray_tpu.ops.paged_flash`) — auto-selected on
    TPU when shapes tile; off-TPU it runs in interpret mode (parity
    tests). ``lens [B]`` is the per-sequence LIVE token count; the
    kernel skips whole blocks past it, making decode work proportional
    to live tokens instead of the table window. ``lens = None``
    derives a conservative bound from ``q_positions`` (every key the
    queries may attend).

    The reference path is the pure-XLA gather (one ``take`` per
    sequence over its block table, f32 softmax): work is
    O(B * C * T * block_size) regardless of true lengths; keep
    ``block_tables`` sized to the serving window, not the model max.
    """
    n_blocks, bs, kvh, d = k_cache.shape
    b, c, h, _ = q.shape
    t = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = "kernel" if ((on_tpu or interpret)
                            and _can_use_paged_kernel(q, k_cache)) \
            else "reference"
    if impl == "kernel":
        from ray_tpu.ops.paged_flash import paged_flash_attention
        if lens is None:
            lens = jnp.max(q_positions, axis=1).astype(jnp.int32) + 1
        if jax.default_backend() != "tpu":
            interpret = True
        return paged_flash_attention(
            q, k_cache, v_cache, block_tables, q_positions, lens,
            sm_scale=sm_scale, block_r=block_r, interpret=interpret)
    if impl != "reference":
        raise ValueError(f"unknown paged attention impl: {impl!r}")
    # Gather each sequence's blocks: [B, T, bs, KVH, D] -> [B, K, KVH, D]
    k = jnp.take(k_cache, block_tables, axis=0).reshape(b, t * bs, kvh, d)
    v = jnp.take(v_cache, block_tables, axis=0).reshape(b, t * bs, kvh, d)
    # key slot j of the gathered view holds absolute position j
    key_pos = jnp.arange(t * bs, dtype=jnp.int32)
    mask = key_pos[None, None, :] <= q_positions[:, :, None]   # [B, C, K]
    if kvh != h:
        # GQA read without materializing a repeated cache copy: group
        # the (tiny) queries onto their kv head and einsum over the
        # grouped axes — XLA broadcasts k/v across the group in the
        # contraction instead of writing an h/kvh-times-larger gather.
        rep = h // kvh
        qg = q.reshape(b, c, kvh, rep, d).astype(jnp.float32)
        s = jnp.einsum("bqgrd,bkgd->bgrqk", qg,
                       k.astype(jnp.float32)) * sm_scale
        s = jnp.where(mask[:, None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bgrqk,bkgd->bqgrd", p, v.astype(jnp.float32))
        return o.reshape(b, c, h, d).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = jnp.where(mask[:, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _can_use_flash(q, k, block_q: int, block_k: int) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if d % 128 != 0:
        return False
    bq, bk = min(block_q, sq), min(block_k, sk)
    return sq % bq == 0 and sk % bk == 0


def multihead_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                        causal: bool = False,
                        sm_scale: Optional[float] = None,
                        mask: Optional[jnp.ndarray] = None,
                        impl: str = "auto",
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None,
                        interpret: bool = False) -> jnp.ndarray:
    """Attention over (batch, seq, heads, head_dim).

    ``impl``: "auto" | "flash" | "reference". Arbitrary ``mask`` forces the
    reference path (the flash kernel handles only the causal structure).
    ``block_q``/``block_k`` of ``None`` (or 0) resolve to chip-aware
    defaults (``flash_attention.default_flash_blocks``).
    """
    if not block_q or not block_k:
        dq_, dk_ = default_flash_blocks(
            q.shape[1], k.shape[1], q.shape[-1],
            chip="cpu" if interpret else None)
        block_q = block_q or dq_
        block_k = block_k or dk_
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        use_flash = (mask is None and (on_tpu or interpret)
                     and _can_use_flash(q, k, block_q, block_k))
        impl = "flash" if use_flash else "reference"
    if impl == "reference" or mask is not None:
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                                   mask=mask)
    if impl != "flash":
        raise ValueError(f"unknown attention impl: {impl!r}")
    qt = jnp.swapaxes(q, 1, 2)    # (B, H, S, D)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = flash_attention(qt, kt, vt, causal=causal, sm_scale=sm_scale,
                        block_q=block_q, block_k=block_k,
                        interpret=interpret)
    return jnp.swapaxes(o, 1, 2)
