"""Worker process: executes tasks and hosts actor instances.

Equivalent of the reference's worker loop (``python/ray/_private/workers/
default_worker.py`` → ``CCoreWorkerProcess.RunTaskExecutionLoop``
``_raylet.pyx:3267`` → ``task_execution_handler`` :2177). The main thread
executes normal tasks and in-order actor tasks (so SIGINT-based
``ray.cancel`` interrupts user code, like the reference); concurrent actors
use a thread pool, async actors an asyncio loop (reference:
``transport/actor_scheduling_queue.h``, ``fiber.h``).

Functions arrive by descriptor key and are fetched once from the
controller's function store then cached (reference:
``python/ray/_private/function_manager.py``).
"""

from __future__ import annotations

import asyncio
import copy
import logging
import os
import queue
import sys
import threading
import time
import traceback
from collections import OrderedDict
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core import events as EV
from ray_tpu.core import protocol as P
from ray_tpu.core.global_state import set_global_worker
from ray_tpu.core.ids import NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.runtime import Runtime, _ArgPlaceholder
from ray_tpu.core.runtime import _DEFER as _RT_DEFER
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.exceptions import TaskCancelledError, TaskError

logger = logging.getLogger(__name__)


class _CallSequencer:
    """In-order admission for direct actor calls (reference: the
    ActorSchedulingQueue's seq_no ordering, actor_scheduling_queue.h).
    The submitter numbers calls per (caller, actor incarnation) at send
    time; this buffer releases them to the executor in that order,
    absorbing the reordering the reliable layer's retransmits can
    introduce (a dropped ACTOR_CALL is redelivered AFTER younger calls).

    Never a hang, always bounded delay: a gap that doesn't fill within
    ``hold_timeout`` is skipped (the missing call may genuinely never
    arrive — its sender can die mid-stream), every stream starts at
    seq 1 (submitters restart numbering per actor incarnation, so a
    reordered FIRST pair is still caught), and seqs below the stream
    cursor run immediately (controller-path retries of already-admitted
    calls). In a fault-free run every call arrives in order, so this is
    a dict lookup per call and nothing is ever held."""

    def __init__(self, deliver, hold_timeout: float = 10.0):
        self._deliver = deliver
        self._hold_timeout = hold_timeout
        self._lock = threading.Lock()
        self._next: Dict[bytes, int] = {}
        self._held: Dict[bytes, Dict[int, dict]] = {}
        self._timers: Dict[bytes, threading.Timer] = {}

    def admit(self, caller: bytes, seq: int, m: dict) -> None:
        with self._lock:
            nxt = self._next.get(caller, 1)
            if seq > nxt:
                held = self._held.setdefault(caller, {})
                held[seq] = m
                if len(held) > 512:
                    # pathological gap (or a stream the sender reset
                    # without us noticing): stop buffering, run in order
                    self._flush_locked(caller)
                elif caller not in self._timers:
                    t = threading.Timer(self._hold_timeout,
                                        self._on_timeout, args=(caller,))
                    t.daemon = True
                    self._timers[caller] = t
                    t.start()
                return
            if seq == nxt:
                nxt += 1
            # delivery happens under the lock: a concurrent timeout
            # flush must not interleave its batch with this one
            self._deliver(m)
            held = self._held.get(caller)
            while held and nxt in held:
                self._deliver(held.pop(nxt))
                nxt += 1
            self._next[caller] = nxt
            if not held:
                t = self._timers.pop(caller, None)
                if t is not None:
                    t.cancel()

    def _on_timeout(self, caller: bytes) -> None:
        with self._lock:
            self._timers.pop(caller, None)
            self._flush_locked(caller)

    def _flush_locked(self, caller: bytes) -> None:
        held = self._held.get(caller)
        if not held:
            return
        # a skipped gap is legal (bounded-delay ordering, never a hang)
        # but worth a line: at sane drop rates it means the missing
        # call's sender died mid-stream
        logger.warning(
            "actor-call stream from %s: predecessor seq %d never "
            "arrived within the reorder wait; running %d held calls",
            caller.hex()[:8], self._next.get(caller, 1), len(held))
        for seq in sorted(held):
            self._deliver(held[seq])
        self._next[caller] = max(self._next.get(caller, 1),
                                 max(held) + 1)
        held.clear()
        t = self._timers.pop(caller, None)
        if t is not None:
            t.cancel()


class WorkerExecutor:
    def __init__(self, runtime: Runtime):
        self.runtime = runtime
        self._queue: "queue.Queue[dict]" = queue.Queue()
        self._functions: Dict[str, Any] = {}
        self.actor_instance = None
        self.actor_spec: Optional[TaskSpec] = None
        self._thread_pool = None
        self._async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._async_sema: Optional[asyncio.Semaphore] = None
        self._stop = False
        #: cancelled task ids -> expiry timestamp (math.inf once matched to
        #: a queued/running task; finite for cancels that matched nothing,
        #: which are kept briefly to cover the dequeue-to-mark window and
        #: then dropped so the map stays bounded)
        self._cancelled: Dict[bytes, float] = {}
        #: (caller identity, template id) -> cached actor-call TaskSpec
        #: template (see the compact-call path in _on_dispatch)
        self._tmpl_cache: "OrderedDict[tuple, TaskSpec]" = OrderedDict()
        #: task id executing on the MAIN thread only — pool/asyncio actor
        #: threads never publish here (a SIGINT raised off the running
        #: thread would corrupt unrelated serial state)
        self._current_tid: Optional[bytes] = None
        self._main_ident = threading.get_ident()
        #: learned wire bytes of the canonical ((), {}) args blob —
        #: lets _resolve_args skip deserializing no-arg fan-out calls
        self._empty_args_blob: Optional[bytes] = None
        #: streaming backpressure: task_id -> cumulative items the
        #: consumer reported consumed (STREAM_CREDIT); producers block
        #: on the condition when produced - consumed hits the window
        self._stream_cond = threading.Condition()
        self._stream_consumed: Dict[bytes, int] = {}
        runtime.stream_credit_handler = self._on_stream_credit
        self._rm = None  # cached runtime metrics handle
        self._stall_metric = None  # cached credit-stall counter handle
        self._block_depth = 0  # main thread blocked in ray.get inside task
        #: serializes the pump thread's dispatch-vs-blocked decision against
        #: on_block's queue drain (without it a dispatch passing the depth
        #: check could land in the queue after the drain and wedge behind
        #: the blocked serial thread)
        self._block_lock = threading.Lock()
        #: per-caller in-order admission for direct actor calls (the
        #: reliable layer redelivers drops out of order; see
        #: _CallSequencer)
        self._sequencer = _CallSequencer(
            self._admit_actor,
            hold_timeout=getattr(runtime.config,
                                 "actor_reorder_wait_s", 10.0))
        self.runtime.set_dispatch_handler(self._on_dispatch)
        self.runtime.block_notifier = self
        self.runtime.busy_probe = \
            lambda: self._current_tid is not None or not self._queue.empty()
        self._install_cancel_handler()

    def _install_cancel_handler(self) -> None:
        """SIGINT delivery is asynchronous: by the time the signal lands the
        cancelled task may have finished and a pipelined neighbour started.
        A targeted handler only raises when the interrupted task really is
        the cancelled one; stray/late signals are ignored instead of
        killing the worker (reference semantics: ray.cancel interrupts the
        task, never the worker process)."""
        import signal

        def handler(signum, frame):
            tid = self._current_tid
            if tid is not None and tid in self._cancelled:
                raise TaskCancelledError(TaskID(tid))

        try:
            signal.signal(signal.SIGINT, handler)
        except ValueError:
            pass  # not on the main thread (driver-embedded executor)

    # ------------------------------------------- blocked-worker protocol
    def on_block(self) -> bool:
        """The serial executor thread is about to wait on a remote result
        (reference: NotifyDirectCallTaskBlocked). Hand unstarted pipeline
        tasks back to the controller so they run elsewhere, and let the
        controller release this lease's cpu while we wait. Only the serial
        thread stalls its queue; concurrent/async actor threads blocking
        don't (their peers keep executing), so they skip the protocol."""
        if threading.get_ident() != self._main_ident:
            return False
        with self._block_lock:
            self._block_depth += 1
            if self._block_depth > 1:
                return True
            # NOTIFY_BLOCKED must precede the handback (FIFO): the
            # controller marks the lease blocked first, so the requeued
            # tasks cannot be pipelined straight back onto this worker
            self.runtime._send(P.NOTIFY_BLOCKED,
                               {"task_id": self._current_tid})
            if self.actor_instance is None:
                handback = []
                while True:
                    try:
                        m = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    spec = m.get("spec")
                    if spec is not None and not spec.is_actor_task \
                            and not spec.is_actor_creation:
                        handback.append(spec)
                    else:
                        self._queue.put(m)
                if handback:
                    self.runtime._send(P.TASK_HANDBACK, {"specs": handback})
        return True

    def on_unblock(self) -> None:
        with self._block_lock:
            self._block_depth -= 1
            if self._block_depth == 0:
                self.runtime._send(P.NOTIFY_UNBLOCKED, {})

    # dispatch arrives on the pump thread; queue for the main thread
    def _on_dispatch(self, m: dict) -> None:
        if m.get("cancel_queued"):
            self._on_cancel(m)
            return
        tmpl = m.get("tmpl")
        if tmpl is not None:
            # Compact actor calls (reference: the per-call task spec is
            # mostly static — the submitter ships it once per method and
            # subsequent calls carry only the dynamic fields; FIFO on
            # the peer channel guarantees the template precedes its
            # compact calls). Saves ~100us of spec pickling per call on
            # each side of the wire.
            key = (m.get("caller") or b"", tmpl)
            if "spec" in m:
                self._tmpl_cache[key] = m["spec"]
                while len(self._tmpl_cache) > 4096:
                    self._tmpl_cache.popitem(last=False)
            else:
                base = self._tmpl_cache.get(key)
                if base is None:
                    # evicted template or lost registration: ask the
                    # caller to resend this call with its full spec —
                    # silently dropping it would hang the caller's get
                    caller = m.get("caller") or b""
                    logger.warning(
                        "compact actor call without template (caller %s "
                        "tmpl %s): requesting resend", caller.hex()[:8],
                        tmpl)
                    if caller:
                        self.runtime._send_direct(
                            caller, P.TMPL_MISS,
                            {"task_id": m.get("task_id"), "tmpl": tmpl})
                    return
                self._tmpl_cache.move_to_end(key)
                spec = copy.copy(base)
                spec.task_id = TaskID(m["task_id"])
                spec.args_blob = m.get("args_blob", b"")
                spec.arg_refs = m.get("arg_refs") or []
                spec.arg_metas = m.get("arg_metas")
                spec.sequence_number = m.get("seq", -1)
                spec.trace = m.get("trace")
                m = dict(m, spec=spec)
        spec: TaskSpec = m["spec"]
        if not spec.is_actor_task and not spec.is_actor_creation:
            # a dispatch racing our NOTIFY_BLOCKED would wedge behind the
            # blocked serial thread — bounce it straight back (the lock
            # makes bounce-vs-drain atomic against on_block)
            with self._block_lock:
                if self._block_depth > 0:
                    # blocked hint: heals the controller's lease state if
                    # its NOTIFY_BLOCKED bookkeeping missed this worker
                    # (otherwise refill ping-pongs dispatches here forever)
                    self.runtime._send(P.TASK_HANDBACK,
                                       {"specs": [spec], "blocked": True})
                    return
                self._queue.put(m)
            return
        if spec.is_actor_task and spec.sequence_number > 0 \
                and spec.owner is not None:
            # per-caller in-order admission: retransmitted calls can
            # arrive after younger ones; the sequencer restores
            # submission order before execution
            self._sequencer.admit(spec.owner.binary(),
                                  spec.sequence_number, m)
            return
        self._admit_actor(m)

    def _admit_actor(self, m: dict) -> None:
        """Queue one actor creation/call for execution (post-ordering)."""
        spec: TaskSpec = m["spec"]
        if self.actor_instance is not None and spec.is_actor_task and (
                self.actor_spec.max_concurrency > 1 or self.actor_spec.is_async_actor):
            # concurrent/async actors bypass the serial queue
            if self.actor_spec.is_async_actor:
                asyncio.run_coroutine_threadsafe(
                    self._execute_async(m), self._async_loop)
            else:
                self._thread_pool.submit(self._execute, m)
        else:
            self._queue.put(m)

    def _on_cancel(self, m: dict) -> None:
        import math
        now = time.time()
        # purge expired unmatched cancels so the map stays bounded
        for k in [k for k, exp in self._cancelled.items() if exp < now]:
            self._cancelled.pop(k, None)
        tid = m["task_id"]
        # mark first so a task popped concurrently sees the flag at the
        # top of _execute, then decide how to deliver the cancel
        self._cancelled[tid] = math.inf
        if self._current_tid == tid:
            # interrupt user code on the main thread (reference:
            # SIGINT-based ray.cancel of a running task); the targeted
            # handler ignores the signal if the task finishes first.
            # Running concurrent/async actor tasks never publish
            # _current_tid — like the reference, they are not
            # interruptible once started.
            import signal
            try:
                os.kill(os.getpid(), signal.SIGINT)
            except Exception:
                pass
            return
        with self._queue.mutex:
            queued = any(item.get("spec") is not None
                         and item["spec"].task_id.binary() == tid
                         for item in self._queue.queue)
        if not queued and self._current_tid != tid:
            # probably already completed (dispatch and cancel ride the same
            # FIFO channel) — but the task may sit in the window between
            # run_loop's dequeue and _execute publishing _current_tid, so
            # keep the marker briefly instead of dropping it outright
            self._cancelled[tid] = now + 5.0

    def run_loop(self) -> None:
        ran_since_gc = False
        while not self._stop:
            try:
                m = self._queue.get(timeout=0.5)
            except queue.Empty:
                if self.runtime._stopped.is_set():
                    break
                # idle: ship any buffered flight-recorder events (e.g.
                # retransmit events from the reliable layer's thread)
                # and the periodic fleet metric snapshot
                self.runtime.recorder.maybe_flush()
                self.runtime.metrics_reporter.maybe_report()
                if ran_since_gc:
                    # idle collection: zero-copy arg values that ended up
                    # in reference cycles hold reader leases on their shm
                    # extents (freed extents stay zombie until released);
                    # an idle worker must not pin them until its next
                    # allocation burst happens to trigger gen-2 GC
                    import gc
                    gc.collect()
                    ran_since_gc = False
                continue
            ran_since_gc = True
            try:
                self._execute(m)
            except (KeyboardInterrupt, TaskCancelledError):
                # backstop for a cancel signal landing in the gap before
                # _execute's try block: report the cancel instead of
                # letting the interrupt kill the worker / drop the task
                logger.warning("cancel interrupt outside task body")
                spec = m.get("spec")
                if spec is not None:
                    err = P.dumps(TaskCancelledError(spec.task_id))
                    self.runtime._send(P.TASK_DONE, {
                        "task_id": spec.task_id.binary(),
                        "trace": spec.trace,
                        "results": [{"object_id": oid.binary()}
                                    for oid in spec.return_ids()],
                        "error": err, "retriable": False,
                        "owner": spec.owner.binary() if spec.owner else None,
                        "owner_notified": False,
                        "is_actor_task": spec.is_actor_task,
                    })

    # --------------------------------------------------------- execution
    def _load_function(self, key: str):
        fn = self._functions.get(key)
        if fn is None:
            blob = self.runtime.fetch_function(key)
            if blob is None:
                raise RuntimeError(f"function {key} not found in function store")
            fn = cloudpickle.loads(blob)
            self._functions[key] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec, inline_args: Dict[bytes, bytes],
                      arg_errors: Dict[bytes, bytes]):
        # seed inline metas so get() short-circuits
        for b, blob in inline_args.items():
            self.runtime.seed_meta(b, {"object_id": b, "inline": blob})
        for b, err in arg_errors.items():
            raise P.loads(err)
        dep_values = []
        for _, oid in spec.arg_refs:
            b = oid.binary()
            meta = {"object_id": b, "inline": inline_args.get(b)}
            if inline_args.get(b) is not None:
                value = self.runtime._materialize(oid, meta)
            else:
                from ray_tpu.core.object_ref import ObjectRef
                value = self.runtime._get_one(
                    ObjectRef(oid, _register=False),
                    self.runtime.config.rpc_timeout_s * 4)
            dep_values.append(value)
        args, kwargs = (), {}
        if spec.args_blob:
            # no-arg fan-out calls all ship the owner's one cached empty
            # blob (runtime.serialize_args) — skip the parse entirely
            blob = spec.args_blob
            if blob == self._empty_args_blob:
                return (), {}
            (args, kwargs), _ = self.runtime.serialization.deserialize_from_view(
                memoryview(blob))
            if not args and not kwargs and not spec.arg_refs:
                self._empty_args_blob = blob
        args = tuple(dep_values[a.index] if isinstance(a, _ArgPlaceholder) else a
                     for a in args)
        kwargs = {k: dep_values[v.index] if isinstance(v, _ArgPlaceholder) else v
                  for k, v in kwargs.items()}
        return args, kwargs

    def _execute(self, m: dict) -> None:
        spec: TaskSpec = m["spec"]
        tid_b = spec.task_id.binary()
        self.runtime.current_task_id = spec.task_id
        on_main = threading.get_ident() == self._main_ident
        if on_main:
            self._current_tid = tid_b
        # install the propagated trace context on THIS thread: tasks
        # this task submits become its causal children, and every
        # lifecycle event below carries the same trace id
        tid_hex = spec.task_id.hex()
        trace_id, span_id, parent_span = EV.task_trace(
            tid_hex, getattr(spec, "trace", None))
        trace_tok = EV.set_context(trace_id, span_id)
        rec = self.runtime.recorder
        rec.record(EV.RUNNING, task=tid_hex, trace=trace_id,
                   span=span_id, parent=parent_span,
                   name=spec.name or spec.function.qualname)
        start = time.time()
        error_blob = None
        retriable = True
        results = []
        values: Optional[list] = None
        stream_metas: Optional[list] = None
        restore_env = None
        try:
            if tid_b in self._cancelled:
                self._cancelled.pop(tid_b, None)
                raise TaskCancelledError(spec.task_id)
            if spec.runtime_env and not spec.is_actor_task \
                    and not spec.is_actor_creation:
                # normal tasks mount their env for THIS task only: pool
                # workers are shared, so env/cwd/sys.path are restored
                # after execution (reference: env-keyed worker pools)
                restore_env = self._apply_runtime_env(spec.runtime_env)
            args, kwargs = self._resolve_args(
                spec, m.get("inline_args") or {}, m.get("arg_errors") or {})
            from ray_tpu.util.tracing import task_execution_span
            with task_execution_span(
                    spec.name or spec.function.qualname,
                    getattr(spec, "trace", None)):
                if spec.is_actor_creation:
                    values = [self._create_actor_instance(
                        spec, args, kwargs)]
                elif spec.is_streaming:
                    # streaming generator task: items are stored and
                    # reported eagerly inside; `values` stays empty and
                    # the trimmed item metas become the TASK_DONE results
                    stream_metas = self._run_streaming(spec, args, kwargs)
                    values = []
                elif spec.is_actor_task:
                    values = self._run_actor_method(spec, args, kwargs)
                else:
                    fn = self._load_function(spec.function.key())
                    out = fn(*args, **kwargs)
                    values = list(out) if spec.num_returns > 1 else [out]
            if not spec.is_streaming and len(values) != spec.num_returns:
                raise ValueError(
                    f"task returned {len(values)} values, expected "
                    f"{spec.num_returns}")
        except KeyboardInterrupt:
            error_blob = P.dumps(TaskCancelledError(spec.task_id))
            retriable = False
        except TaskCancelledError as e:
            error_blob = P.dumps(e)
            retriable = False
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, TaskError):
                err = e
            else:
                err = TaskError.from_exception(
                    spec.name or spec.function.qualname, e)
            error_blob = P.dumps(err)
            retriable = bool(spec.retry_exceptions)
            logger.warning("task %s failed:\n%s", spec.name,
                           err.traceback_str if hasattr(err, "traceback_str") else err)
        # user code is done: step out of the cancel window NOW so a late
        # SIGINT cannot interrupt result storage / the TASK_DONE send
        if on_main:
            self._current_tid = None
        self._cancelled.pop(tid_b, None)
        EV.restore(trace_tok)
        if restore_env is not None:
            try:
                restore_env()
            except Exception:
                logger.exception("runtime_env restore failed")
        if error_blob is None:
            for i, value in enumerate(values):
                oid = ObjectID.for_task_return(spec.task_id, i + 1)
                try:
                    meta = self.runtime._store_value(oid, value, notify=False)
                except BaseException as e:  # noqa: BLE001
                    error_blob = P.dumps(TaskError.from_exception(
                        spec.name or spec.function.qualname, e))
                    results = []
                    break
                results.append(meta)
            if stream_metas is not None:
                # streamed items were stored and owner-reported in-band;
                # TASK_DONE ships the trimmed metas so the controller
                # records shm locations + lineage (inline items stay
                # owner-local — the owner got their bytes via
                # STREAM_ITEM, the controller only needs existence)
                results = stream_metas
        if error_blob is not None:
            results = [{"object_id": oid.binary()}
                       for oid in spec.return_ids()]
        # Result meta goes DIRECT to the owner (reference: task replies go
        # straight to the submitting core worker, not through the GCS);
        # TASK_DONE to the controller keeps the object directory / task
        # table / lease accounting consistent, off the latency path.
        # Retriable errors are NOT final — the controller owns the retry
        # decision, so those defer to its TASK_RESULT forward.
        owner_b = spec.owner.binary() if spec.owner else None
        may_retry = (error_blob is not None and retriable
                     and spec.max_retries != 0)
        direct_ok = owner_b is not None and not may_retry
        result_msg = None
        driver_leased = bool(m.get("driver_leased"))
        if direct_ok:
            # shallow-copy the metas: TASK_DONE carries the same list,
            # and a same-process owner stores these dicts directly.
            # Streaming tasks ship NO result metas here: the owner's
            # authoritative per-item metas arrived via STREAM_ITEM, and
            # the trimmed TASK_DONE copies must not overwrite them.
            result_msg = (owner_b, P.TASK_RESULT, {
                "task_id": tid_b,
                "trace": spec.trace,
                "results": [] if spec.is_streaming else
                [dict(r, error=error_blob) for r in results],
                "error": error_blob,
                "actor_id": spec.actor_id.binary() if spec.is_actor_task
                else None,
                # controller-path dispatch: the controller records these
                # results in its directory, so the owner must promote
                # owner-local returns to tracked (covers retry re-routes
                # of originally-direct tasks too)
                "via_controller": not driver_leased
                and not spec.is_actor_task,
            })
        done_results = results
        if direct_ok and self.runtime._owner_local and error_blob is None \
                and (driver_leased or spec.is_actor_task):
            # (The direct RES push is reliably delivered — ack +
            # retransmit, core/reliable.py — so the trim is safe under
            # injected drops too; the owner's grace-then-probe fallback
            # now only covers worker death with the result unflushed.)
            # owner-local mode, direct dispatch (driver lease / actor
            # call): the owner (which just got TASK_RESULT) is the
            # authority for inline results — the controller neither
            # records nor needs their bytes. Shm results keep full
            # metas (the directory tracks extents). Controller-path
            # tasks are NOT trimmed: the controller records their
            # results and unparks dependents from them.
            done_results = [r if r.get("node_id") is not None
                            else {"object_id": r["object_id"],
                                  "size": r.get("size", 0)}
                            for r in results]
        done = {
            "task_id": tid_b,
            "trace": spec.trace,
            "results": done_results,
            "error": error_blob,
            "retriable": retriable,
            "owner": owner_b,
            "owner_notified": direct_ok,
            # flag only — re-shipping the whole spec (args blob included)
            # on every actor call would tax the hot path
            "is_actor_task": spec.is_actor_task,
        }
        if stream_metas is not None:
            done["streaming"] = True
            done["stream_count"] = len(stream_metas)
        if m.get("driver_leased"):
            # direct driver-leased dispatch: tell the controller to skip
            # worker/lease bookkeeping; retriable errors ship the spec so
            # the controller can re-route through the normal scheduler
            done["driver_leased"] = True
            if may_retry:
                done["spec"] = spec
        if may_retry and spec.is_actor_task:
            # direct actor calls have no controller-side PendingTask; ship
            # the spec so the controller can re-route the retry
            done["spec"] = spec
        # one queue handoff for both messages: each _out_q put can wake
        # the flusher thread (a futex round-trip per task adds up).
        # Direct-path completions (driver-leased / actor calls) defer
        # their TASK_DONE a few ms: the owner already has the result via
        # RES, the controller only records — batching the accounting
        # frees the shared core for the caller's latency path. Errors
        # stay immediate (the controller owns the retry decision).
        defer_done = error_blob is None and direct_ok \
            and (driver_leased or spec.is_actor_task)
        done_tgt = _RT_DEFER if defer_done else None
        done_msg = (done_tgt, P.TASK_DONE, done)
        if result_msg is not None:
            self.runtime._send_many([result_msg, done_msg])
        else:
            self.runtime._send_many([done_msg])
        try:
            rm = self._rm
            if rm is None:
                from ray_tpu.core.metric_defs import runtime_metrics
                base = runtime_metrics()
                rm = self._rm = (
                    base.tasks_finished.bound({"outcome": "ok"}),
                    base.tasks_finished.bound({"outcome": "error"}),
                    base.task_exec_seconds.bound())
            rm[1 if error_blob else 0].inc()
            rm[2].observe(time.time() - start)
        except Exception:
            pass
        self.runtime.record_span(
            spec.name or spec.function.qualname, start, time.time() - start,
            task_id=spec.task_id.hex())
        rec.record(EV.FAILED if error_blob is not None else EV.FINISHED,
                   task=tid_hex, trace=trace_id, span=span_id,
                   parent=parent_span,
                   name=spec.name or spec.function.qualname,
                   dur_s=round(time.time() - start, 6))
        rec.maybe_flush()
        self.runtime.current_task_id = self.runtime._driver_task_id

    async def _execute_async(self, m: dict) -> None:
        # None = the loop's default executor, which actor setup replaced
        # with a max_concurrency-sized pool (the asyncio default would
        # cap concurrency at min(32, cpus+4) and deadlock against user
        # run_in_executor work — see _create_actor_instance).
        async with self._async_sema:
            await asyncio.get_event_loop().run_in_executor(
                None, lambda: self._execute_async_inner(m))

    def _execute_async_inner(self, m: dict) -> None:
        # For async actors, coroutine methods run on the loop; delegate
        # through _execute with coroutine awaiting inside _run_actor_method.
        self._execute(m)

    # ------------------------------------------------------------- actors
    def _create_actor_instance(self, spec: TaskSpec, args, kwargs):
        cls = self._load_function(spec.function.key())
        if spec.runtime_env:
            self._apply_runtime_env(spec.runtime_env)
        self.actor_instance = cls(*args, **kwargs)
        self.actor_spec = spec
        self.runtime._current_actor_id = spec.actor_id
        if spec.max_concurrency > 1 and not spec.is_async_actor:
            from concurrent.futures import ThreadPoolExecutor
            self._thread_pool = ThreadPoolExecutor(spec.max_concurrency)
        if spec.is_async_actor:
            self._async_loop = asyncio.new_event_loop()
            # Dedicated executor installed as the loop's default.
            # asyncio's built-in default executor is min(32, cpus+4)
            # threads — on small hosts that silently caps actor
            # concurrency below max_concurrency, and DEADLOCKS when
            # user code shares the default executor: a streaming call
            # occupies one thread for its whole life, and the user
            # coroutine's own run_in_executor work queues behind
            # further calls that are waiting for those same threads.
            # Sized 2x + margin so every admitted call (semaphore caps
            # them at max_concurrency) can nest one run_in_executor of
            # its own without exhausting the pool.
            from concurrent.futures import ThreadPoolExecutor
            self._async_pool = ThreadPoolExecutor(
                2 * max(2, spec.max_concurrency) + 2,
                thread_name_prefix="actor-async-exec")
            self._async_loop.set_default_executor(self._async_pool)
            t = threading.Thread(target=self._async_loop.run_forever,
                                 name="actor-asyncio", daemon=True)
            t.start()
            fut = asyncio.run_coroutine_threadsafe(
                self._make_sema(spec.max_concurrency), self._async_loop)
            fut.result()
        return None

    async def _make_sema(self, n: int) -> None:
        self._async_sema = asyncio.Semaphore(max(1, n))

    def _run_actor_method(self, spec: TaskSpec, args, kwargs):
        if self.actor_instance is None:
            from ray_tpu.exceptions import ActorDiedError
            raise ActorDiedError(spec.actor_id, "no instance in this worker")
        name = spec.function.qualname
        if name == "__ray_ready__":
            return [True]
        if name == "__ray_call__":
            # generic invoke: fn(actor_instance, *args, **kwargs)
            fn, rest = args[0], args[1:]
            out = fn(self.actor_instance, *rest, **kwargs)
            return list(out) if spec.num_returns > 1 else [out]
        if name == "__ray_terminate__":
            self._stop = True
            threading.Thread(target=self._delayed_exit, daemon=True).start()
            return [None]
        method = getattr(self.actor_instance, name)
        out = method(*args, **kwargs)
        if asyncio.iscoroutine(out):
            if self._async_loop is not None and \
                    threading.current_thread().name != "actor-asyncio":
                fut = asyncio.run_coroutine_threadsafe(out, self._async_loop)
                out = fut.result()
            else:
                out = asyncio.new_event_loop().run_until_complete(out)
        return list(out) if spec.num_returns > 1 else [out]

    # ------------------------------------------------ streaming generators
    def _on_stream_credit(self, m: dict) -> None:
        """Pump-thread: the consumer reported cumulative consumption —
        open the producer's backpressure window. Credits are monotonic;
        stale/reordered ones are ignored."""
        with self._stream_cond:
            tid = m.get("task_id")
            cur = self._stream_consumed.get(tid)
            if cur is not None and m.get("consumed", 0) > cur:
                self._stream_consumed[tid] = m["consumed"]
                self._stream_cond.notify_all()

    def _stream_wait_window(self, tid_b: bytes, produced: int,
                            window: int) -> None:
        """Block until the consumer's credit opens the window (produced
        - consumed < window). Interruptible: ray.cancel (SIGINT on the
        main thread, the cancel flag elsewhere) and executor shutdown
        break the wait — a producer must never outlive its consumer's
        interest.

        A credit wait is an open-ended remote wait, exactly like a
        ray.get inside a task: the blocked-worker protocol applies
        (NOTIFY_BLOCKED + pipeline handback), or a slow consumer would
        wedge every task queued behind this one on the serial thread
        and pin a cpu the cluster could use."""

        def open_locked() -> bool:
            return produced - self._stream_consumed.get(tid_b, 0) < window

        with self._stream_cond:
            if open_locked():
                return  # fast path: no protocol round-trip
        token = self.runtime._enter_blocked()
        stall_t0 = time.monotonic()
        try:
            with self._stream_cond:
                while not open_locked():
                    if tid_b in self._cancelled or self._stop or \
                            self.runtime._stopped.is_set():
                        raise TaskCancelledError(TaskID(tid_b))
                    self._stream_cond.wait(0.1)
        finally:
            self.runtime._exit_blocked(token)
            stalled = time.monotonic() - stall_t0
            # producer blocked on the backpressure window: the signal
            # Podracer-style overlap tuning needs (a persistently
            # stalled producer means the consumer is the bottleneck)
            try:
                rm = self._stall_metric
                if rm is None:
                    from ray_tpu.core.metric_defs import runtime_metrics
                    rm = self._stall_metric = \
                        runtime_metrics().credit_stall_seconds.bound()
                if stalled > 0:
                    rm.inc(stalled)
            except Exception:
                pass
            self.runtime.recorder.record(
                EV.CREDIT_STALL, task=tid_b.hex(),
                seconds=round(stalled, 6), produced=produced)

    def _agen_iter(self, agen):
        """Bridge an async generator to a sync iterator: on an async
        actor, items are pulled through the actor's event loop (user
        code may await shared state there); elsewhere a private loop
        drives it. The finally runs on close() too (cancelled stream):
        the source's aclose() must fire promptly so its own finally
        blocks (e.g. the serve replica's ongoing-count decrement) run,
        instead of waiting for some distant GC."""
        if self._async_loop is not None:
            try:
                while True:
                    try:
                        fut = asyncio.run_coroutine_threadsafe(
                            agen.__anext__(), self._async_loop)
                        yield fut.result()
                    except StopAsyncIteration:
                        return
            finally:
                try:
                    asyncio.run_coroutine_threadsafe(
                        agen.aclose(), self._async_loop).result(5.0)
                except Exception:
                    pass
        else:
            loop = asyncio.new_event_loop()
            try:
                while True:
                    try:
                        yield loop.run_until_complete(agen.__anext__())
                    except StopAsyncIteration:
                        return
            finally:
                try:
                    loop.run_until_complete(agen.aclose())
                except Exception:
                    pass
                loop.close()

    def _make_stream_iterator(self, spec: TaskSpec, args, kwargs):
        """Invoke the task body and normalize its result to a sync
        iterator of yielded items."""
        import inspect
        if spec.is_actor_task:
            if self.actor_instance is None:
                from ray_tpu.exceptions import ActorDiedError
                raise ActorDiedError(spec.actor_id,
                                     "no instance in this worker")
            method = getattr(self.actor_instance, spec.function.qualname)
            out = method(*args, **kwargs)
        else:
            fn = self._load_function(spec.function.key())
            out = fn(*args, **kwargs)
        if inspect.iscoroutine(out):
            # an async (non-generator) method returning a generator:
            # resolve it first. inspect, not asyncio: the asyncio
            # predicate also matches plain generators (legacy
            # generator-coroutines), which must stream as-is.
            if self._async_loop is not None and \
                    threading.current_thread().name != "actor-asyncio":
                out = asyncio.run_coroutine_threadsafe(
                    out, self._async_loop).result()
            else:
                out = asyncio.new_event_loop().run_until_complete(out)
        if inspect.isasyncgen(out):
            return self._agen_iter(out)
        if inspect.isgenerator(out) or hasattr(out, "__iter__"):
            return iter(out)
        raise TypeError(
            f"num_returns='streaming' requires "
            f"{spec.name or spec.function.qualname!r} to return a "
            f"generator, got {type(out).__name__}")

    def _run_streaming(self, spec: TaskSpec, args, kwargs) -> list:
        """Execute a generator task: eagerly store each yielded item as
        its own object and report it (STREAM_ITEM, reliable) the moment
        it exists; STREAM_EOF closes the stream (reference:
        ``ReportGeneratorItemReturns``, core_worker.cc). Consumer-paced:
        blocks at the backpressure window until credits arrive. Returns
        the trimmed item metas for TASK_DONE (controller records shm
        locations + lineage off them).

        Error semantics: a mid-stream exception is delivered AS the
        failing item (typed, ordered) followed by EOF — unless the task
        may retry (retry_exceptions + retries budgeted), in which case
        nothing terminal is emitted and the replay re-reports the
        stream from index 1 (the owner dedups)."""
        from ray_tpu.core.ids import ObjectID as _OID
        rt = self.runtime
        tid_b = spec.task_id.binary()
        owner_b = spec.owner.binary() if spec.owner else None
        me = rt.worker_id.binary()
        window = spec.backpressure or getattr(
            rt.config, "generator_backpressure_num_objects", 64)
        with self._stream_cond:
            self._stream_consumed.setdefault(tid_b, 0)
        metas = []
        produced = 0
        it = None

        tid_hex = spec.task_id.hex()
        trace_id, span_id, parent_span = EV.task_trace(
            tid_hex, getattr(spec, "trace", None))

        def send_item(index: int, meta: dict,
                      nbytes: Optional[int] = None) -> None:
            rt.recorder.record(EV.YIELDED, task=tid_hex, trace=trace_id,
                               span=span_id, parent=parent_span,
                               index=index,
                               **({"nbytes": nbytes} if nbytes else {}))
            if owner_b:
                rt._send_direct(owner_b, P.STREAM_ITEM, {
                    "task_id": tid_b, "index": index, "meta": meta,
                    "worker": me, "trace": spec.trace})
            rt.recorder.maybe_flush()
            # long-lived generators (pipeline stages, data pipelines)
            # may never hit the idle loop: yield time is their metric
            # heartbeat
            rt.metrics_reporter.maybe_report()

        def send_eof(count: int) -> None:
            if owner_b:
                rt._send_direct(owner_b, P.STREAM_EOF, {
                    "task_id": tid_b, "count": count, "worker": me,
                    "trace": spec.trace})

        try:
            it = self._make_stream_iterator(spec, args, kwargs)
            while True:
                if window > 0:
                    self._stream_wait_window(tid_b, produced, window)
                if tid_b in self._cancelled:
                    raise TaskCancelledError(spec.task_id)
                try:
                    value = next(it)
                except StopIteration:
                    break
                # device-array fast path: fetch device->host NOW, on
                # the generator's thread, so the store+report path (and
                # any lock it takes) never blocks on an accelerator
                # transfer; the serializer then ships the host view
                # out-of-band instead of through the pickle stream
                from ray_tpu.core.serialization import to_host
                value = to_host(value)
                produced += 1
                oid = _OID.for_task_return(spec.task_id, produced)
                meta = rt._store_value(oid, value, notify=True)
                metas.append(
                    meta if meta.get("node_id") is not None
                    else {"object_id": meta["object_id"],
                          "size": meta.get("size", 0)})
                send_item(produced, meta, meta.get("size"))
        except (KeyboardInterrupt, TaskCancelledError):
            # cancelled (usually by the consumer closing the stream):
            # EOF for any straggler consumer, then the normal cancel
            # reporting path
            send_eof(produced)
            raise
        except BaseException as e:  # noqa: BLE001
            if spec.retry_exceptions and spec.max_retries != 0:
                # a retry may replay the stream cleanly — emit nothing
                # terminal (the owner dedups the replayed prefix)
                raise
            # typed mid-stream exception delivered as the failing item
            produced += 1
            oid = _OID.for_task_return(spec.task_id, produced)
            err = e if isinstance(e, TaskError) else \
                TaskError.from_exception(
                    spec.name or spec.function.qualname, e)
            item_meta = {"object_id": oid.binary(), "error": P.dumps(err)}
            rt.seed_meta(oid.binary(), item_meta)
            send_item(produced, item_meta)
            send_eof(produced)
            raise err
        finally:
            with self._stream_cond:
                self._stream_consumed.pop(tid_b, None)
            # close the (possibly abandoned) generator NOW: its finally
            # blocks — and for async gens the bridged aclose() — must
            # not wait for GC (a cancelled serve stream would otherwise
            # leak the replica's ongoing-count until collection)
            if it is not None:
                try:
                    it.close()
                except Exception:
                    pass
        send_eof(produced)
        return metas

    @staticmethod
    def _apply_runtime_env(env: dict):
        """env_vars + cached working_dir/py_modules mounts (reference:
        the worker half of the runtime-env agent; pip/conda rejected at
        submission — hermetic TPU image). Returns the restore callable
        (used for normal tasks; actors keep their env for life)."""
        from ray_tpu.core.runtime_env import apply_runtime_env
        return apply_runtime_env(env)


def _orphan_watchdog(parent_pid: int,
                     node_pid: Optional[int] = None) -> None:
    """Exit when the spawning node manager's process dies (reference:
    workers poll raylet liveness and die with it — core_worker.cc
    CheckForRayletFailure). Workers start in their own session, so no
    SIGHUP arrives; without this they outlive dead clusters.

    Zygote-forked workers are NOT children of the node manager (the
    double fork reparents them to init), and worse, the getppid()
    captured at main() can be the short-lived intermediate fork parent
    — its exit then looked exactly like node-manager death and killed
    ~20% of workers in actor bursts. When the node manager's pid is
    known (RAY_TPU_NODE_PID), poll THAT process directly."""
    while True:
        time.sleep(2.0)
        if node_pid is not None:
            try:
                os.kill(node_pid, 0)
                continue
            except ProcessLookupError:
                pass
            except PermissionError:
                continue
        elif os.getppid() == parent_pid:
            continue
        logging.getLogger(__name__).warning(
            "node manager process died; worker exiting")
        os._exit(1)


def main() -> None:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s: %(message)s")
    dump_after = os.environ.get("RAY_TPU_WORKER_FAULTDUMP")
    if dump_after:
        # debugging aid: dump all thread stacks to the worker log every
        # N seconds (hang diagnosis; reference: `ray stack`)
        import faulthandler
        faulthandler.dump_traceback_later(
            float(dump_after), repeat=True)
    node_pid = os.environ.get("RAY_TPU_NODE_PID")
    threading.Thread(target=_orphan_watchdog,
                     args=(os.getppid(),
                           int(node_pid) if node_pid else None),
                     daemon=True).start()
    # Honor an explicit platform override before any task imports jax.
    # (Env-var JAX_PLATFORMS alone is not enough in environments whose
    # sitecustomize re-pins it at interpreter start — tests set
    # RAY_TPU_JAX_PLATFORM=cpu to force the virtual CPU mesh in workers.)
    platform = os.environ.get("RAY_TPU_JAX_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
    shm_session = os.environ["RAY_TPU_SHM_SESSION"]
    if os.environ.get("RAY_TPU_CHAOS_SEED"):
        # header line so a red chaos run maps worker logs to the seeded
        # decision stream that produced them
        logging.getLogger(__name__).warning(
            "chaos: worker %s under fault injection (seed=%s stream "
            "id=%s)", worker_id.hex()[:12],
            os.environ.get("RAY_TPU_CHAOS_SEED"),
            os.environ.get("RAY_TPU_CHAOS_ID", ""))
    boot_t0 = time.perf_counter()
    bootprof = os.environ.get("RAY_TPU_WORKER_BOOTPROF")

    def mark(stage: str) -> None:
        if bootprof:
            print(f"BOOT {stage} {time.perf_counter() - boot_t0:.3f} "
                  f"cpu={time.process_time():.3f}", flush=True)

    runtime = Runtime("worker", session_dir, node_id, worker_id, shm_session)
    mark("runtime")
    set_global_worker(runtime)
    runtime.register()
    mark("registered")
    executor = WorkerExecutor(runtime)
    mark("executor")
    profile_out = os.environ.get("RAY_TPU_PROFILE_WORKER")
    if profile_out:
        # drop a cProfile of the execution loop at exit (debugging aid:
        # per-task overhead hunting; reference: `ray stack`/py-spy fill
        # this role). SIGTERM becomes a clean loop stop so the stats
        # actually flush.
        import cProfile
        import signal as _sig
        _sig.signal(_sig.SIGTERM,
                    lambda *_: setattr(executor, "_stop", True))
        pr = cProfile.Profile()
        try:
            pr.runcall(executor.run_loop)
        finally:
            pr.dump_stats(f"{profile_out}.{os.getpid()}")
            runtime.shutdown()
        return
    try:
        executor.run_loop()
    finally:
        runtime.shutdown()


if __name__ == "__main__":
    main()
