"""Runtime environments: per-task/actor execution context.

Reference: ``python/ray/_private/runtime_env/agent/runtime_env_agent.py``
:161 — the agent materializes ``working_dir``/``py_modules`` packages
into a content-addressed URI cache with reference-counted GC, plus
``pip``/``conda`` env builds. TPU-native subset: the image is hermetic
(pip/conda installs at task time would desync a pod's hosts), so those
raise up front; ``working_dir`` and ``py_modules`` are packaged into a
content-hashed cache under the session dir shared by every node on the
host, and workers mount them onto ``sys.path``. ``env_vars`` pass
through.
"""

from __future__ import annotations

import hashlib
import os
import re
import shutil
import sys
from typing import Any, Dict, Optional

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
#: staging dirs end with ".tmp-<pid>-<hex8>" (see _package_dir); a
#: substring test would misclassify cache entries whose SOURCE dir
#: happened to contain ".tmp-" in its name
_STAGING_RE = re.compile(r"\.tmp-\d+-[0-9a-f]{8}$")
_MAX_PACKAGE_BYTES = 512 << 20

#: options the reference supports that a hermetic TPU image must reject
#: loudly rather than silently ignore
_UNSUPPORTED = ("pip", "conda", "container", "uv")


def _hash_dir(path: str) -> str:
    """Digest of the tree's CONTENTS (a size+mtime digest would serve
    stale cache hits for same-length rewrites within one clock second)."""
    h = hashlib.sha256()
    total = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for name in sorted(files):
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, path)
            h.update(rel.encode())
            try:
                with open(fp, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        total += len(chunk)
                        if total > _MAX_PACKAGE_BYTES:
                            raise ValueError(
                                f"runtime_env package {path!r} exceeds "
                                f"{_MAX_PACKAGE_BYTES >> 20} MiB")
                        h.update(chunk)
            except OSError:
                continue
    return h.hexdigest()[:16]


def _cache_root(session_dir: str) -> str:
    return os.path.join(session_dir, "runtime_resources")


def _touch(path: str) -> None:
    """Refresh a cache entry's LRU stamp (gc_cache orders by mtime)."""
    try:
        os.utime(path, None)
    except OSError:
        pass


def _package_dir(session_dir: str, src: str, wrap: bool = False) -> str:
    """Copy ``src`` into the content-addressed cache (no-op when the
    same content is already cached — reference: URI cache hits).

    ``wrap=True`` (py_modules) nests the copy one level deep under its
    own basename so putting the RETURNED path on ``sys.path`` makes
    ``import <basename>`` work, matching Ray's documented semantics."""
    import uuid
    src = os.path.abspath(src)
    if not os.path.isdir(src):
        raise ValueError(f"runtime_env path {src!r} is not a directory")
    digest = _hash_dir(src)
    name = os.path.basename(src.rstrip("/"))
    # wrapped (py_modules) and unwrapped (working_dir) layouts of the
    # same tree are distinct cache entries — keying on content alone
    # would serve whichever layout was cached first to both consumers
    layout = "mod" if wrap else "dir"
    dest = os.path.join(
        _cache_root(session_dir), f"{name}-{digest}-{layout}")
    if os.path.isdir(dest):
        # bump the entry's LRU stamp: copytree preserved the SOURCE
        # tree's mtime, and gc_cache orders by mtime, so without an
        # explicit touch a live entry can be evicted as "oldest"
        _touch(dest)
    else:
        # unique staging dir: concurrent preparers of the same env must
        # not rmtree/copytree over each other's half-written trees
        tmp = f"{dest}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        target = os.path.join(tmp, name) if wrap else tmp
        shutil.copytree(
            src, target,
            ignore=shutil.ignore_patterns(*_EXCLUDE_DIRS, "*.pyc"))
        # copystat gave the staging root the SOURCE's mtime — restamp it
        # so a concurrent gc_cache can't mistake it for an orphan
        _touch(tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            # either a concurrent preparer won the race with identical
            # content (fine), or the staging tree was lost (not fine —
            # returning a path that doesn't exist would make workers
            # silently skip the mount)
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(dest):
                raise RuntimeError(
                    f"runtime_env packaging of {src!r} failed: staging "
                    f"dir vanished before publish (cache: {dest})")
        _touch(dest)
    return dest


#: (session_dir, canonical env) -> (monotonic ts, resolved env). Bounds
#: driver-side cost: a hot .remote() loop must not re-walk/re-hash the
#: tree per submission; a short TTL still picks up on-disk edits.
_prepare_memo: Dict[Any, Any] = {}
_PREPARE_TTL_S = 10.0


def prepare_runtime_env(env: Optional[Dict[str, Any]],
                        session_dir: str) -> Optional[Dict[str, Any]]:
    """Driver-side: validate + package. Returns the resolved env whose
    paths all live in the session cache (workers just mount them)."""
    if not env:
        return env
    for key in _UNSUPPORTED:
        if env.get(key):
            raise ValueError(
                f"runtime_env[{key!r}] is not supported on the hermetic "
                f"TPU image (bake dependencies into the image instead)")
    import json
    import time
    memo_key = (session_dir, json.dumps(env, sort_keys=True, default=str))
    hit = _prepare_memo.get(memo_key)
    now = time.monotonic()
    if hit is not None and now - hit[0] < _PREPARE_TTL_S:
        return hit[1]
    out = dict(env)
    if env.get("working_dir"):
        out["working_dir"] = _package_dir(session_dir, env["working_dir"])
    if env.get("py_modules"):
        out["py_modules"] = [_package_dir(session_dir, p, wrap=True)
                             for p in env["py_modules"]]
    gc_cache(session_dir)
    if len(_prepare_memo) > 256:
        _prepare_memo.clear()
    _prepare_memo[memo_key] = (now, out)
    return out


def apply_runtime_env(env: Dict[str, Any]):
    """Worker-side: mount a prepared env into this process (reference:
    the worker half of the runtime-env agent handshake). Returns a
    restore callable: pool workers are SHARED, so a normal task's env
    must not leak into unrelated later tasks (actors keep theirs for
    life and never call it). Imported modules stay in sys.modules —
    unloading live modules is not safe — matching the caveat the
    reference solves with env-keyed worker pools."""
    saved_env = {k: os.environ.get(k)
                 for k in (env.get("env_vars") or {})}
    saved_cwd = os.getcwd()
    saved_path = list(sys.path)
    for k, v in (env.get("env_vars") or {}).items():
        os.environ[k] = str(v)
    for mod_dir in env.get("py_modules") or []:
        if os.path.isdir(mod_dir):
            _touch(mod_dir)
            if mod_dir not in sys.path:
                sys.path.insert(0, mod_dir)
    wd = env.get("working_dir")
    if wd and os.path.isdir(wd):
        _touch(wd)
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)

    def restore():
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        try:
            os.chdir(saved_cwd)
        except OSError:
            pass
        sys.path[:] = saved_path

    return restore


def gc_cache(session_dir: str, keep: int = 16) -> int:
    """Drop least-recently-used cache entries beyond ``keep`` (reference:
    URI reference counting + cache GC; sessions are short-lived here so
    LRU-by-mtime is sufficient). Returns number of entries removed."""
    import time
    root = _cache_root(session_dir)
    now = time.time()
    removed = 0
    try:
        entries = []
        for e in os.listdir(root):
            p = os.path.join(root, e)
            try:
                mtime = os.path.getmtime(p)
            except OSError:
                continue
            if _STAGING_RE.search(e):
                # staging dir: in use by a live preparer if fresh,
                # orphaned by a crashed one if stale
                if now - mtime >= 60.0:
                    shutil.rmtree(p, ignore_errors=True)
                    removed += 1
                continue
            entries.append((mtime, p))
    except FileNotFoundError:
        return 0
    entries.sort(reverse=True)
    for mtime, path in entries[keep:]:
        # grace window: entries are utime-stamped on every access (see
        # _package_dir/apply_runtime_env), so anything touched recently
        # may be in use by an in-flight task
        if now - mtime < 60.0:
            continue
        shutil.rmtree(path, ignore_errors=True)
        removed += 1
    return removed
