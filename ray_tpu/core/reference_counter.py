"""Reference counting for distributed GC.

The reference implements fully decentralized ownership with a borrowing
protocol (``src/ray/core_worker/reference_count.h:61``): each object's owner
tracks borrowers via pubsub (WaitForRefRemoved). This build keeps the same
*observable* semantics (objects live while any process holds a ref or an
in-flight task depends on them; freed when the last ref dies) with a
single-controller accounting design: every process runs a local
``ReferenceCounter`` that batches count deltas to the controller, which is
the authority that triggers deletion when an object's global count reaches
zero. Contained refs discovered during (de)serialization produce the same
delta messages a borrow registration would.

Rationale: the control plane here is already a single authority (GCS-
equivalent); piggy-backing GC on it removes the hardest distributed
protocol in the reference while preserving the API contract. Lineage
pinning (``task_manager.h:432``) lives controller-side as well.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

from ray_tpu.core.ids import ObjectID


class ReferenceCounter:
    """Process-local counts + batched delta reporting."""

    def __init__(self, flush_fn: Optional[Callable[[Dict[bytes, int]], None]] = None):
        self._lock = threading.Lock()
        self._local: Dict[ObjectID, int] = {}
        # counts of in-flight task submissions using this ref as an arg
        self._submitted: Dict[ObjectID, int] = {}
        self._pending_deltas: Dict[bytes, int] = {}
        self._flush_fn = flush_fn
        self._flush_threshold = 256
        # fired (outside the lock) when an object's combined local +
        # submitted count drops to zero — the owner's eager-free hook
        self._on_owner_zero: Optional[Callable[[ObjectID], None]] = None
        # decrefs from ObjectRef.__del__ — GC can run __del__ on the
        # thread that already holds _lock (mid-_delta dict op), so
        # __del__ must never lock: it appends here (GIL-atomic) and the
        # next locked operation drains the queue
        self._deferred_decrefs: "deque[ObjectID]" = deque()
        #: owner-local objects (reference: in-process store objects the
        #: GCS never hears about): counts are kept locally but produce NO
        #: controller deltas until promoted (ref escape / controller-path
        #: submit). Keyed by object id binary.
        self._untracked: set = set()

    def set_flush_fn(self, fn: Callable[[Dict[bytes, int]], None]) -> None:
        self._flush_fn = fn

    def set_owner_zero_fn(self, fn: Callable[[ObjectID], None]) -> None:
        self._on_owner_zero = fn

    # -- ObjectRef lifecycle hooks --
    def add_local_reference(self, ref) -> None:
        self._delta(ref.id(), +1, self._local)

    def remove_local_reference(self, ref) -> None:
        # __del__-safe: lock-free defer (see _deferred_decrefs)
        self._deferred_decrefs.append(ref.id())

    # -- task submission pinning --
    def add_submitted_task_ref(self, object_id: ObjectID) -> None:
        self._delta(object_id, +1, self._submitted)

    def remove_submitted_task_ref(self, object_id: ObjectID) -> None:
        self._delta(object_id, -1, self._submitted)

    def _apply_locked(self, object_id: ObjectID, d: int,
                      table: Dict[ObjectID, int],
                      zeros: List[ObjectID]) -> None:
        """Apply one delta. Caller holds the lock; owner-zero events are
        appended to ``zeros`` and must be fired after release."""
        n = table.get(object_id, 0) + d
        if n <= 0:
            table.pop(object_id, None)
        else:
            table[object_id] = n
        key = object_id.binary()
        untracked = key in self._untracked
        if d < 0 and n <= 0 \
                and self._local.get(object_id, 0) == 0 \
                and self._submitted.get(object_id, 0) == 0:
            zeros.append(object_id)
            if untracked:
                # fully dead: no promotion record needed, set stays bounded
                self._untracked.discard(key)
        if untracked:
            return
        # A +1/-1 pair inside one flush window still nets to a 0-delta
        # entry that MUST be flushed: dropping it would hide the
        # object's entire lifecycle from the controller (never "ever
        # positive" -> its entry and shm extent would leak forever).
        self._pending_deltas[key] = \
            self._pending_deltas.get(key, 0) + d

    def _drain_deferred_locked(self, zeros: List[ObjectID]) -> None:
        while True:
            try:
                oid = self._deferred_decrefs.popleft()
            except IndexError:
                return
            self._apply_locked(oid, -1, self._local, zeros)

    def _fire(self, flush: Optional[Dict[bytes, int]],
              zeros: List[ObjectID]) -> None:
        if flush and self._flush_fn:
            self._flush_fn(flush)
        if zeros and self._on_owner_zero is not None:
            for oid in zeros:
                self._on_owner_zero(oid)

    def _delta(self, object_id: ObjectID, d: int, table: Dict[ObjectID, int]) -> None:
        flush = None
        zeros: List[ObjectID] = []
        with self._lock:
            self._drain_deferred_locked(zeros)
            self._apply_locked(object_id, d, table, zeros)
            if len(self._pending_deltas) >= self._flush_threshold:
                flush = self._pending_deltas
                self._pending_deltas = {}
        self._fire(flush, zeros)

    # -- owner-local (untracked) objects --
    def mark_untracked(self, object_id: ObjectID) -> None:
        """Suppress controller deltas for this object: the owner tracks it
        locally only. Must be called BEFORE the first add_local_reference
        for the object."""
        with self._lock:
            self._untracked.add(object_id.binary())

    def is_untracked(self, object_id_b: bytes) -> bool:
        with self._lock:
            return object_id_b in self._untracked

    def promote(self, object_id: ObjectID) -> int:
        """Stop suppressing deltas and inject the object's CURRENT live
        count as one pending delta, so the controller's table picks up as
        if it had been tracked from the start. Returns the injected count,
        or -1 if the object was not untracked (already promoted / never
        suppressed). An injected 0 is meaningful: it tells the controller
        the object lived and fully died (frees the directory entry)."""
        flush = None
        zeros: List[ObjectID] = []
        with self._lock:
            self._drain_deferred_locked(zeros)
            key = object_id.binary()
            if key not in self._untracked:
                n = -1
            else:
                self._untracked.discard(key)
                n = self._local.get(object_id, 0) + \
                    self._submitted.get(object_id, 0)
                self._pending_deltas[key] = \
                    self._pending_deltas.get(key, 0) + n
                if len(self._pending_deltas) >= self._flush_threshold:
                    flush = self._pending_deltas
                    self._pending_deltas = {}
        self._fire(flush, zeros)
        return n

    def flush(self) -> None:
        zeros: List[ObjectID] = []
        with self._lock:
            self._drain_deferred_locked(zeros)
            deltas = self._pending_deltas
            self._pending_deltas = {}
        self._fire(deltas or None, zeros)

    def local_count(self, object_id: ObjectID) -> int:
        zeros: List[ObjectID] = []
        with self._lock:
            self._drain_deferred_locked(zeros)
            n = self._local.get(object_id, 0) + \
                self._submitted.get(object_id, 0)
        self._fire(None, zeros)
        return n

    def all_counts(self) -> Dict[bytes, int]:
        """Aggregate live counts, for re-seeding a restarted controller's
        global table (its counts died with it)."""
        zeros: List[ObjectID] = []
        with self._lock:
            self._drain_deferred_locked(zeros)
            out: Dict[bytes, int] = {}
            for table in (self._local, self._submitted):
                for oid, n in table.items():
                    b = oid.binary()
                    if b in self._untracked:
                        continue  # owner-local: the controller never
                        # tracked it and must not start now
                    out[b] = out.get(b, 0) + n
        self._fire(None, zeros)
        return out


class GlobalRefTable:
    """Controller-side aggregate (the deletion authority).

    Tracks per-object: global refcount, owner, locations, lineage task, and
    a lineage pin while any downstream object might need reconstruction.
    """

    def __init__(self, on_zero: Callable[[ObjectID], None]):
        self._lock = threading.Lock()
        self._counts: Dict[bytes, int] = {}
        self._ever_positive: Dict[bytes, bool] = {}
        #: Recently-released ids (bounded FIFO). Needed because a worker's
        #: TASK_DONE races the owner's release deltas on separate sockets:
        #: without a tombstone the controller would resurrect an object
        #: entry whose refcount already hit zero and pin its shm extent
        #: forever (the zero event never fires twice).
        self._released: "OrderedDict[bytes, None]" = OrderedDict()
        self._released_cap = 65536
        self._on_zero = on_zero

    def apply_deltas(self, deltas: Dict[bytes, int]) -> None:
        zeroed = []
        with self._lock:
            for key, d in deltas.items():
                n = self._counts.get(key, 0) + d
                if d >= 0:
                    # d == 0 is a client-side netted +1/-1 pair: the
                    # object existed and was fully dropped within one
                    # flush window — it must still count as having been
                    # referenced, or its entry never becomes freeable
                    self._ever_positive[key] = True
                if n <= 0:
                    self._counts.pop(key, None)
                    if self._ever_positive.pop(key, False):
                        zeroed.append(ObjectID(key))
                        self._released[key] = None
                        while len(self._released) > self._released_cap:
                            self._released.popitem(last=False)
                else:
                    self._counts[key] = n
                    self._released.pop(key, None)
        for oid in zeroed:
            self._on_zero(oid)

    def cancel_release(self, object_id_b: bytes) -> None:
        """Undo a zero-event's tombstone: the controller decided the
        object must live (active waiters hold refs whose deltas are
        still in flight). Without this, the tombstone makes
        _h_task_done discard the object's upcoming location records."""
        with self._lock:
            self._released.pop(object_id_b, None)

    def force_release(self, object_id_b: bytes) -> bool:
        """Owner-side eager free: drop this object's counts and tombstone
        it so late deltas / completion records can't resurrect it.
        Returns False if it was already released."""
        with self._lock:
            if object_id_b in self._released:
                return False
            self._counts.pop(object_id_b, None)
            self._ever_positive.pop(object_id_b, None)
            self._released[object_id_b] = None
            while len(self._released) > self._released_cap:
                self._released.popitem(last=False)
            return True

    def is_released(self, object_id_b: bytes) -> bool:
        """True if this object's refcount already hit zero (it must not be
        resurrected by a late completion record)."""
        with self._lock:
            return object_id_b in self._released

    def count(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id.binary(), 0)

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._counts)
