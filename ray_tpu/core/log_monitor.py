"""Log monitor: stream worker/job output to the driver terminal.

Reference: ``python/ray/_private/log_monitor.py:103`` — the LogMonitor
daemon tails per-worker log files and publishes lines; drivers print
them prefixed with the producing worker. Here the driver tails the
session's log directory directly (one host owns a session's logs; no
pubsub hop needed) with the same visible behavior:
``(worker-ab12cd pid=N)`` prefixes, new files picked up as workers
start, rotation-safe via inode checks.
"""

from __future__ import annotations

import os
import re
import sys
import threading
from typing import Dict, Optional, TextIO

_WORKER_RE = re.compile(r"(worker|job)-([0-9a-f-]+)\.(out|log)$")


class LogMonitor:
    def __init__(self, session_dir: str, out: Optional[TextIO] = None,
                 poll_s: float = 0.5):
        self.log_dir = os.path.join(session_dir, "logs")
        self.out = out or sys.stderr
        self.poll_s = poll_s
        self._offsets: Dict[str, int] = {}   # path -> bytes consumed
        self._inodes: Dict[str, int] = {}
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # existing content predates this driver: start at EOF, stream
        # only what happens from now on (reference behavior)
        self._scan(seed_only=True)
        self._thread = threading.Thread(
            target=self._loop, name="log-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._scan()  # final drain so short-lived workers aren't lost

    def _loop(self) -> None:
        while not self._stopped.wait(self.poll_s):
            try:
                self._scan()
            except Exception:
                pass

    def _scan(self, seed_only: bool = False) -> None:
        try:
            names = os.listdir(self.log_dir)
        except FileNotFoundError:
            return
        for name in names:
            m = _WORKER_RE.search(name)
            if not m:
                continue
            path = os.path.join(self.log_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if self._inodes.get(path) != st.st_ino:
                # new or rotated file
                self._inodes[path] = st.st_ino
                self._offsets[path] = st.st_size if seed_only else 0
            if seed_only:
                continue
            off = self._offsets.get(path, 0)
            if st.st_size <= off:
                continue
            prefix = f"({m.group(1)}-{m.group(2)[:8]})"
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(1 << 20)
            except OSError:
                continue
            # consume whole lines only; a partial tail waits for more —
            # unless the window is full with no newline at all (a giant
            # single line), which must be flushed as-is or the file's
            # tail would stall at this offset forever
            cut = chunk.rfind(b"\n")
            if cut < 0:
                if len(chunk) < (1 << 20):
                    continue
                consumed = len(chunk)
                text = chunk.decode(errors="replace")
            else:
                consumed = cut + 1
                text = chunk[:cut].decode(errors="replace")
            self._offsets[path] = off + consumed
            for line in text.splitlines():
                if "__ray_tpu_tqdm__:" in line:
                    from ray_tpu.experimental.tqdm_ray import render_record
                    if render_record(line, self.out):
                        continue
                print(f"{prefix} {line}", file=self.out)
