"""Internal runtime metric definitions.

Reference: ``src/ray/stats/metric_defs.cc`` — the fixed set of runtime
metrics every Ray process exports (task counts by state, scheduler
queue depths, object-store usage, gRPC/ZMQ traffic, worker counts).
Here the same catalog is defined over :mod:`ray_tpu.util.metrics`;
runtime components call the ``record_*`` helpers on their hot paths
(cheap: process-local counters, exported with user metrics through the
same Prometheus endpoint).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_tpu.util.metrics import Counter, Gauge, Histogram

_lock = threading.Lock()
_defs: Optional["RuntimeMetrics"] = None


class RuntimeMetrics:
    """The runtime metric catalog (created once per process)."""

    def __init__(self):
        # -- tasks (reference: ray_tasks metric, by State/Name)
        self.tasks_submitted = Counter(
            "runtime_tasks_submitted_total",
            "Tasks submitted by this process")
        self.tasks_finished = Counter(
            "runtime_tasks_finished_total",
            "Task completions observed", tag_keys=("outcome",))
        self.task_exec_seconds = Histogram(
            "runtime_task_execution_seconds",
            "Wall time of task execution on this worker")
        # -- scheduler (reference: scheduler_tasks / scheduler_unscheduleable)
        self.sched_queued = Gauge(
            "runtime_scheduler_queued_tasks",
            "Tasks in the controller's ready queues")
        self.sched_pending_args = Gauge(
            "runtime_scheduler_pending_args_tasks",
            "Tasks parked waiting for dependencies")
        self.sched_infeasible = Gauge(
            "runtime_scheduler_infeasible_tasks",
            "Tasks whose resource shape currently fits no node")
        # -- objects (reference: object_store_memory / object_directory)
        self.object_store_bytes = Gauge(
            "runtime_object_store_used_bytes",
            "Bytes used in the local shared-memory store")
        self.object_store_objects = Gauge(
            "runtime_object_store_num_objects",
            "Sealed objects resident in the local store")
        self.objects_tracked = Gauge(
            "runtime_object_directory_size",
            "Objects the controller tracks cluster-wide")
        self.puts = Counter(
            "runtime_puts_total", "ray_tpu.put calls")
        self.put_bytes = Counter(
            "runtime_put_bytes_total", "Bytes written by put")
        self.materialized_bytes = Counter(
            "runtime_object_bytes_materialized_total",
            "Bytes of object payloads this process materialized from "
            "the shm store / remote holders (inbound transfer "
            "accounting: what ray_tpu.get actually moved here)")
        # -- workers / actors (reference: actors-by-state, worker counts)
        self.workers_alive = Gauge(
            "runtime_workers_alive", "Worker processes registered")
        self.actors_alive = Gauge(
            "runtime_actors_alive", "Actors in ALIVE state")
        self.actors_pending = Gauge(
            "runtime_actors_pending", "Actors awaiting placement/start")
        # -- transport (reference: grpc_server_req counters)
        self.messages_sent = Counter(
            "runtime_messages_sent_total",
            "Control-plane messages sent", tag_keys=("kind",))
        self.message_batch_size = Histogram(
            "runtime_message_batch_size",
            "Messages coalesced per wire batch")
        # -- reliable delivery (core/reliable.py hot paths)
        self.retransmits = Counter(
            "runtime_reliable_retransmits_total",
            "Reliable-layer retransmissions", tag_keys=("type",))
        self.ack_batch_size = Histogram(
            "runtime_reliable_ack_batch_size",
            "Wire seqs acknowledged per MSG_ACK message",
            boundaries=[1, 2, 5, 10, 20, 50, 100, 250])
        self.ack_rtt = Histogram(
            "runtime_reliable_ack_rtt_seconds",
            "Send-to-ack latency of reliably-delivered messages "
            "(retransmit attempts included)")
        self.dup_dropped = Counter(
            "runtime_reliable_dup_dropped_total",
            "Retransmit duplicates discarded by the receive dedup")
        self.delivery_failed = Counter(
            "runtime_reliable_delivery_failed_total",
            "Messages abandoned at the attempt cap "
            "(DeliveryFailedError)")
        # -- streaming generators
        self.credit_stall_seconds = Counter(
            "runtime_stream_credit_stall_seconds_total",
            "Seconds streaming producers spent blocked on the "
            "backpressure window waiting for STREAM_CREDIT")
        # -- serve LLM engine (serve/llm_engine.py): per-replica
        # scheduler signals — the queue-latency/occupancy family the
        # autoscaler consumes (ROADMAP item 1)
        self.serve_queue_depth = Gauge(
            "serve_engine_queue_depth",
            "Requests waiting for a decode slot on this replica")
        self.serve_batch_occupancy = Histogram(
            "serve_engine_batch_occupancy",
            "Active decode slots per batched decode step",
            boundaries=[1, 2, 4, 8, 16, 32, 64])
        self.serve_ttft = Histogram(
            "serve_engine_ttft_seconds",
            "Submit-to-first-token latency (chunked prefill included)",
            boundaries=[0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10])
        self.serve_tokens = Counter(
            "serve_engine_tokens_total",
            "Tokens generated by this replica's engine")
        self.serve_tokens_per_s = Gauge(
            "serve_engine_tokens_per_s",
            "Engine decode throughput since start")
        self.serve_prefix_hits = Counter(
            "serve_engine_prefix_hit_blocks_total",
            "Prompt KV blocks whose prefill was skipped via a radix "
            "prefix-cache match (shared or copy-on-write)")
        self.serve_blocks_shared = Gauge(
            "serve_engine_blocks_shared",
            "KV blocks currently referenced by more than one sequence")
        self.serve_spec_accept = Histogram(
            "serve_engine_spec_accept_ratio",
            "Accepted/drafted ratio per speculative verify step "
            "(prompt-lookup multi-token decode)",
            boundaries=[0.0, 0.25, 0.5, 0.75, 1.0])
        # -- disaggregated prefill/decode hand-off (serve/disagg.py)
        self.serve_kv_ship_bytes = Counter(
            "serve_kv_ship_bytes_total",
            "Wire bytes of finished prefill KV blocks shipped toward "
            "decode replicas (bf16 raw or int8 blockwise payloads)",
            tag_keys=("wire",))
        self.serve_kv_ship_seconds = Histogram(
            "serve_kv_ship_seconds",
            "Ship-to-adopt wall per disagg hand-off (prefill export "
            "complete to decode-side blocks adopted)",
            boundaries=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                        0.5, 1, 2.5])
        self.serve_prefix_migrated = Counter(
            "serve_prefix_migrated_blocks_total",
            "Warm radix-trie KV blocks exported off draining replicas "
            "and adopted by survivors (warm-prefix migration)",
            tag_keys=("dir",))
        # -- flight recorder (core/events.py)
        self.events_dropped = Counter(
            "runtime_events_dropped_total",
            "Flight-recorder events dropped at the ring-buffer cap")
        # -- fleet metrics plane (core/metrics_plane.py)
        self.metric_reports_dropped = Counter(
            "runtime_metric_reports_dropped_total",
            "METRIC_REPORT snapshots abandoned by this process "
            "(superseded in-flight reports beyond the pending bound, "
            "or a down send path)", tag_keys=("reason",))
        self.metrics_update_errors = Counter(
            "runtime_metrics_update_errors_total",
            "update_from_state gauge-refresh failures (a broken gauge "
            "path is visible here instead of silently swallowed)",
            tag_keys=("source",))
        # -- training telemetry (models/training.py + MPMDPipeline):
        # the live versions of what bench.py records offline
        self.train_step_wall = Histogram(
            "train_step_wall_seconds",
            "Wall time per optimizer step (dispatch to completion)",
            boundaries=[0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
                        10, 30])
        self.train_tokens_per_s = Gauge(
            "train_tokens_per_s",
            "Training throughput over the last telemetry window")
        self.train_loss = Gauge(
            "train_loss", "Most recent training loss")
        self.train_grad_norm = Gauge(
            "train_grad_norm", "Most recent global gradient norm")
        self.train_mfu = Gauge(
            "train_mfu_pct",
            "Model FLOP utilization (%) from the bench FLOP model "
            "(flops_per_token x tokens/s over the chip's bf16 peak)")
        # -- MPMD pipeline (parallel/mpmd_pipeline.py)
        self.pipeline_mailbox_depth = Gauge(
            "pipeline_stage_mailbox_depth",
            "Microbatches parked in a stage actor's mailboxes "
            "(activations + grads + targets)", tag_keys=("stage",))
        self.pipeline_bubble = Gauge(
            "pipeline_bubble_fraction",
            "Measured pipeline bubble of the most recent step")
        # -- slice autoscaling (autoscaler/slices.py): the gang unit's
        # lifecycle as fleet gauges
        self.slices_up = Gauge(
            "autoscaler_slices_up",
            "TPU slices fully joined (every host VM registered and "
            "alive)")
        self.slice_hosts_pending = Gauge(
            "autoscaler_slice_hosts_pending",
            "Host VMs of acquired slices that have not registered yet")
        self.slice_drain_seconds = Histogram(
            "autoscaler_slice_drain_seconds",
            "Notice-to-release drain duration per slice (maintenance "
            "or idle scale-down)",
            boundaries=[0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120])
        # -- slice arbitration (autoscaler/arbiter.py) + SLO admission
        # (serve/handle.py): train+serve colocation signals
        self.arbiter_preemptions = Counter(
            "autoscaler_arbiter_preemptions_total",
            "Training slices drained by the slice arbiter for the "
            "serve fleet", tag_keys=("reason",))
        self.arbiter_returns = Counter(
            "autoscaler_arbiter_returns_total",
            "Borrowed slices handed back to training after serve "
            "pressure ebbed past hysteresis", tag_keys=("reason",))
        self.admission_rejected = Counter(
            "serve_admission_rejected_total",
            "Requests shed by SLO-aware admission before reaching a "
            "replica queue", tag_keys=("tenant", "priority"))
        # -- per-request tracing (serve/request_trace.py, serve/slo.py)
        self.serve_slo_violations = Counter(
            "serve_slo_violations_total",
            "Per-phase SLO budget trips flagged by the serve SLO "
            "watchdog; each trip flips its request's trace to "
            "always-ship", tag_keys=("phase",))
        self.request_spans_shipped = Counter(
            "serve_request_spans_shipped_total",
            "Request-trace span batches shipped to the controller "
            "under tail sampling (slow, failed/shed, or 1-in-N)")
        # -- memory / health (reference: memory_manager worker kills)
        self.oom_worker_kills = Counter(
            "runtime_oom_worker_kills_total",
            "Workers killed by the memory monitor")
        self.node_mem_percent = Gauge(
            "runtime_node_memory_used_percent",
            "Node memory utilization")


def runtime_metrics() -> RuntimeMetrics:
    global _defs
    with _lock:
        if _defs is None:
            _defs = RuntimeMetrics()
        return _defs


#: sources whose update_from_state failure has already been logged —
#: the counter keeps counting, the log fires once per (process, source)
_update_error_logged: set = set()


def _count_update_error(m: "RuntimeMetrics", source: str) -> None:
    try:
        m.metrics_update_errors.inc(tags={"source": source})
    except Exception:
        pass
    if source not in _update_error_logged:
        _update_error_logged.add(source)
        import logging
        logging.getLogger(__name__).warning(
            "update_from_state: %s gauge refresh failed (logged once; "
            "further failures count in "
            "runtime_metrics_update_errors_total)", source,
            exc_info=True)


def update_from_state(controller=None, store_stats: Optional[Dict] = None,
                      node_stats: Optional[Dict] = None) -> None:
    """Refresh gauge families from component state (called from the
    heartbeat/stats paths — gauges snapshot, counters accumulate).
    A failing gauge path is counted in
    ``runtime_metrics_update_errors_total`` and logged once instead of
    silently swallowed."""
    m = runtime_metrics()
    if controller is not None:
        try:
            m.sched_queued.set(
                sum(len(q) for q in controller.ready_queues.values()))
            m.sched_pending_args.set(sum(
                1 for t in controller.tasks.values()
                if t.state == "PENDING_DEPS"))
            m.objects_tracked.set(len(controller.objects))
            m.workers_alive.set(sum(
                len(n.all_workers) for n in controller.nodes.values()))
            m.actors_alive.set(sum(
                1 for a in controller.actors.values()
                if a.state == "ALIVE"))
            m.actors_pending.set(sum(
                1 for a in controller.actors.values()
                if a.state in ("PENDING", "STARTING", "RESTARTING")))
        except Exception:
            _count_update_error(m, "controller")
    if store_stats:
        try:
            m.object_store_bytes.set(store_stats.get("used_bytes", 0))
            m.object_store_objects.set(
                store_stats.get("num_objects", 0))
        except Exception:
            _count_update_error(m, "store")
    if node_stats:
        try:
            pct = node_stats.get("mem_percent")
            if pct is not None:
                m.node_mem_percent.set(pct)
        except Exception:
            _count_update_error(m, "node")
