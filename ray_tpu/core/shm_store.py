"""Shared-memory object store: the plasma equivalent.

Design (vs. reference ``src/ray/object_manager/plasma/``): plasma is a
store *server* inside the raylet serving clients over a unix socket with fd
passing (``fling.cc``); objects live in mmap'd segments carved by dlmalloc.
Here every node has a session directory under ``/dev/shm``; each sealed
object is one mmap'd file named by its ObjectID hex. Clients attach by name
— same zero-copy property (page-cache-shared mappings), no fd passing
needed. Create/Seal/Get/Release/Delete semantics and LRU eviction with
ref pinning match ``object_lifecycle_manager.h`` / ``eviction_policy.h``;
capacity overflow falls back to a disk directory (plasma "fallback
allocation") and spilling (``local_object_manager.h:41``).

An optional C++ slab allocator (ray_tpu/_native) accelerates small-object
placement; the mmap layout is identical so readers are agnostic.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_SHM_ROOT = "/dev/shm"


class _Mapped:
    __slots__ = ("mm", "view", "size", "path")

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = size
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.view = memoryview(self.mm)

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass
        try:
            self.mm.close()
        except Exception:
            pass


class ShmObjectStore:
    """Node-local store. One instance lives in the node manager process
    (the authority for eviction); workers use `ShmClient` views keyed by the
    same session name."""

    def __init__(self, session_name: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self.session_name = session_name
        self.dir = os.path.join(_SHM_ROOT, session_name)
        os.makedirs(self.dir, exist_ok=True)
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._used = 0
        # LRU order: oldest first (reference: eviction_policy.h LRUCache)
        self._sealed: "OrderedDict[ObjectID, int]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._spilled: Dict[ObjectID, str] = {}

    # --- server-side bookkeeping (node manager) ---
    def on_sealed(self, object_id: ObjectID, size: int,
                  grace: bool = False) -> None:
        # ``grace`` (fresh-arrival spill grace) is a NativeShmStore
        # refinement; the python fallback store accepts and ignores it
        with self._lock:
            self._sealed[object_id] = size
            self._used += size
            self._maybe_evict_locked()

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._sealed or object_id in self._spilled

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._delete_locked(object_id)

    def _delete_locked(self, object_id: ObjectID) -> None:
        size = self._sealed.pop(object_id, None)
        if size is not None:
            self._used -= size
            try:
                os.unlink(self._path(object_id))
            except FileNotFoundError:
                pass
        spath = self._spilled.pop(object_id, None)
        if spath:
            try:
                os.unlink(spath)
            except FileNotFoundError:
                pass

    def _maybe_evict_locked(self) -> None:
        """Evict-by-spill LRU unpinned objects when over capacity."""
        if self._used <= self.capacity:
            return
        for oid in list(self._sealed.keys()):
            if self._used <= self.capacity:
                break
            if oid in self._pinned:
                continue
            if self.spill_dir:
                self._spill_locked(oid)
            else:
                self._delete_locked(oid)

    def _spill_locked(self, object_id: ObjectID) -> None:
        size = self._sealed.get(object_id)
        if size is None:
            return
        src = self._path(object_id)
        dst = os.path.join(self.spill_dir, object_id.hex())
        try:
            os.replace(src, dst) if os.stat(src).st_dev == os.stat(self.spill_dir).st_dev \
                else self._copy_spill(src, dst)
        except OSError:
            self._copy_spill(src, dst)
        self._sealed.pop(object_id, None)
        self._used -= size
        self._spilled[object_id] = dst

    @staticmethod
    def _copy_spill(src: str, dst: str) -> None:
        with open(src, "rb") as f, open(dst, "wb") as g:
            while True:
                chunk = f.read(1 << 22)
                if not chunk:
                    break
                g.write(chunk)
        os.unlink(src)

    def maybe_evict(self) -> None:
        """Background spill/eviction toward the budget (node heartbeat)."""
        with self._lock:
            self._maybe_evict_locked()

    def make_room(self, bytes_needed: int) -> int:
        """Spill/evict LRU unpinned objects until ``bytes_needed`` of
        capacity is free (see NativeShmStore.make_room)."""
        freed = 0
        with self._lock:
            for oid in list(self._sealed.keys()):
                if self.capacity - self._used >= bytes_needed:
                    break
                if oid in self._pinned:
                    continue
                size = self._sealed.get(oid, 0)
                if self.spill_dir:
                    self._spill_locked(oid)
                else:
                    self._delete_locked(oid)
                freed += size
        return freed

    def maybe_restore(self, object_id: ObjectID) -> bool:
        """Restore a spilled object back into shm (reference:
        local_object_manager.h AsyncRestoreSpilledObject)."""
        with self._lock:
            spath = self._spilled.get(object_id)
            if spath is None:
                return object_id in self._sealed
            size = os.stat(spath).st_size
            m = _Mapped(self._path(object_id), size, create=True)
            with open(spath, "rb") as f:
                f.readinto(m.view)
            m.close()
            os.unlink(spath)
            self._spilled.pop(object_id, None)
            self._sealed[object_id] = size
            self._used += size
            return True

    def contents(self):
        """[(object_id_binary, size)] of every sealed (incl. spilled)
        object — the node re-announces these to a restarted controller."""
        with self._lock:
            out = [(oid.binary(), sz) for oid, sz in self._sealed.items()]
            out.extend((oid.binary(), 0) for oid in self._spilled)
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_objects": len(self._sealed),
                "num_spilled": len(self._spilled),
                "num_pinned": len(self._pinned),
            }

    def _path(self, object_id: ObjectID) -> str:
        return os.path.join(self.dir, object_id.hex())

    def destroy(self) -> None:
        with self._lock:
            for oid in list(self._sealed.keys()) + list(self._spilled.keys()):
                self._delete_locked(oid)
        try:
            os.rmdir(self.dir)
        except OSError:
            pass


def make_store(session_name: str, capacity_bytes: int,
               spill_dir: Optional[str] = None):
    """Store factory: native C++ segment when buildable, else the
    Python file-per-object store."""
    from ray_tpu import _native
    if _native.load() is not None:
        try:
            from ray_tpu.core.native_store import NativeShmStore
            return NativeShmStore(session_name, capacity_bytes,
                                  spill_dir=spill_dir)
        except OSError:
            pass
    return ShmObjectStore(session_name, capacity_bytes,
                          spill_dir=spill_dir)


def make_client(session_name: str):
    """Client factory: the segment file's existence marks a native-store
    session (the node manager creates it before workers/drivers join)."""
    from ray_tpu import _native
    seg = os.path.join(_SHM_ROOT, f"{session_name}.seg")
    if os.path.exists(seg) and _native.load() is not None:
        from ray_tpu.core.native_store import NativeShmClient
        return NativeShmClient(session_name)
    return ShmClient(session_name)


class ShmClient:
    """Worker/driver-side client: create+seal and zero-copy get by name.

    Equivalent of ``plasma::PlasmaClient`` (plasma/client.h). Attach is by
    filename under the session shm dir; mappings are cached per process.
    """

    def __init__(self, session_name: str):
        self.dir = os.path.join(_SHM_ROOT, session_name)
        self._mapped: Dict[ObjectID, _Mapped] = {}
        self._lock = threading.Lock()

    def _path(self, object_id: ObjectID) -> str:
        return os.path.join(self.dir, object_id.hex())

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        if size == 0:
            size = 1
        m = _Mapped(self._path(object_id) + ".building", size, create=True)
        with self._lock:
            self._mapped[object_id] = m
        return m.view

    def seal(self, object_id: ObjectID) -> int:
        """Atomically publish the object (rename building -> final)."""
        os.replace(self._path(object_id) + ".building", self._path(object_id))
        with self._lock:
            m = self._mapped.get(object_id)
        return m.size if m else 0

    def put_bytes(self, object_id: ObjectID, data) -> int:
        view = self.create(object_id, len(data))
        view[: len(data)] = data
        return self.seal(object_id)

    def get_view(self, object_id: ObjectID, timeout: float = 0.0) -> Optional[memoryview]:
        """Zero-copy view of a sealed object; None if absent."""
        with self._lock:
            m = self._mapped.get(object_id)
            if m is not None:
                return m.view
        path = self._path(object_id)
        deadline = time.monotonic() + timeout
        while True:
            try:
                size = os.stat(path).st_size
                m = _Mapped(path, size, create=False)
                with self._lock:
                    self._mapped[object_id] = m
                return m.view
            except FileNotFoundError:
                if time.monotonic() >= deadline:
                    return None
                time.sleep(0.001)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            if object_id in self._mapped:
                return True
        return os.path.exists(self._path(object_id))

    def release(self, object_id: ObjectID) -> None:
        with self._lock:
            m = self._mapped.pop(object_id, None)
        if m is not None:
            m.close()

    def close(self) -> None:
        with self._lock:
            for m in self._mapped.values():
                m.close()
            self._mapped.clear()
