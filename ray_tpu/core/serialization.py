"""Serialization: cloudpickle + pickle-5 out-of-band zero-copy buffers.

Equivalent of the reference's ``python/ray/_private/serialization.py``
(SerializationContext :110, serialize :482, deserialize_objects :393):

- cloudpickle for arbitrary Python (functions, classes, closures);
- pickle protocol 5 with out-of-band ``PickleBuffer``s so large numpy /
  jax-host arrays are written to the shared-memory store without a copy and
  mapped back as zero-copy views on read;
- custom reducers for ObjectRef (borrowing) and ActorHandle.

Wire format of a serialized object:
    [u32 n_buffers][u64 len_meta][meta pickle bytes][buffer 0][buffer 1]...
buffers 8-byte aligned, each prefixed by u64 length.
"""

from __future__ import annotations

import io
import pickle
import struct
import threading
from typing import List, Optional, Tuple

import cloudpickle

_ALIGN = 64  # align buffers for vectorized readers / dlpack import


class SerializedObject:
    """A serialized value: metadata bytes + zero-copy buffer views."""

    __slots__ = ("meta", "buffers", "contained_refs")

    def __init__(self, meta: bytes, buffers: List[memoryview],
                 contained_refs: list):
        self.meta = meta
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        n = 12 + len(self.meta)
        for b in self.buffers:
            n = _aligned(n + 8) + b.nbytes
        return n

    def write_to(self, target: memoryview) -> int:
        """Write the wire format into ``target``; returns bytes written."""
        struct.pack_into("<IQ", target, 0, len(self.buffers), len(self.meta))
        off = 12
        target[off:off + len(self.meta)] = self.meta
        off += len(self.meta)
        for b in self.buffers:
            off = _aligned(off + 8) - 8
            struct.pack_into("<Q", target, off, b.nbytes)
            off += 8
            flat = b.cast("B") if b.ndim != 1 or b.format != "B" else b
            target[off:off + b.nbytes] = flat
            off += b.nbytes
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes())
        n = self.write_to(memoryview(out))
        return bytes(out[:n])


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


_thread_local = threading.local()


def get_active_context() -> Optional["SerializationContext"]:
    return getattr(_thread_local, "active_ctx", None)


class SerializationContext:
    """Per-worker serializer. Tracks refs contained in serialized values
    (for the borrowing protocol) and refs found while deserializing."""

    def __init__(self, worker=None):
        self.worker = worker
        self._custom_serializers = {}

    # -- hooks called from ObjectRef.__reduce__ --
    # ref lists live in thread-local state so concurrent (de)serialize calls
    # (threaded actors) don't clobber each other's tracking
    def record_contained_ref(self, ref) -> None:
        getattr(_thread_local, "contained", []).append(ref)

    def record_deserialized_ref(self, ref) -> None:
        getattr(_thread_local, "deserialized", []).append(ref)

    def register_custom_serializer(self, cls, serializer, deserializer):
        self._custom_serializers[cls] = (serializer, deserializer)

    # -- main entry points --
    def serialize(self, value) -> SerializedObject:
        buffers: List[pickle.PickleBuffer] = []
        _thread_local.active_ctx = self
        _thread_local.contained = contained = []
        try:
            value = _pre_serialize(value)
            try:
                # C-pickle fast path: ~5x cheaper than building a
                # CloudPickler per call, and every __reduce__ hook
                # (ObjectRef borrowing, custom serializers applied in
                # _pre_serialize) fires identically. Task results are
                # overwhelmingly plain data; closures/local classes
                # raise and fall back. __main__ globals DON'T raise —
                # C-pickle happily encodes them by reference, which a
                # worker (whose __main__ is worker.py) can't resolve —
                # so any STACK_GLOBAL against __main__ (its module name
                # appears literally in the stream) also falls back to
                # cloudpickle's by-value treatment.
                # The pickler carries a scoped dispatch-table entry for
                # device arrays: any jax.Array ANYWHERE in the value
                # (streamed pipeline activations, (loss, aux) tuples)
                # ships as a raw out-of-band buffer instead of riding
                # the pickle stream in-band.
                sink = io.BytesIO()
                p = pickle.Pickler(sink, protocol=5,
                                   buffer_callback=buffers.append)
                dt = _device_array_dispatch()
                if dt is not None:
                    p.dispatch_table = dt
                p.dump(value)
                meta = sink.getvalue()
                if b"__main__" in meta:
                    raise pickle.PicklingError("__main__ global")
            except (pickle.PicklingError, pickle.PickleError, TypeError,
                    AttributeError):
                buffers.clear()
                contained.clear()
                meta = cloudpickle.dumps(
                    value, protocol=5, buffer_callback=buffers.append)
        finally:
            _thread_local.active_ctx = None
            _thread_local.contained = []
        views = []
        for pb in buffers:
            v = pb.raw()
            views.append(v)
        return SerializedObject(meta, views, contained)

    def deserialize(self, meta: bytes, buffers: List[memoryview]) -> Tuple[object, list]:
        """Returns (value, deserialized_refs)."""
        _thread_local.active_ctx = self
        _thread_local.deserialized = deserialized = []
        try:
            value = pickle.loads(meta, buffers=buffers)
        finally:
            _thread_local.active_ctx = None
            _thread_local.deserialized = []
        return value, list(deserialized)

    def deserialize_from_view(self, view: memoryview) -> Tuple[object, list]:
        value, refs, _ = self.deserialize_from_view_tracked(view)
        return value, refs

    def deserialize_from_view_tracked(
            self, view: memoryview) -> Tuple[object, list, list]:
        """Like deserialize_from_view, but also returns the out-of-band
        buffer views handed to pickle. Zero-copy consumers (arrow
        buffers, numpy bases) hold references to EXACTLY these
        memoryview objects for as long as any alias of the data lives —
        they are the correct anchors for reader-lease lifetime (a
        finalizer on the VALUE fires too early: a table can die while
        its sliced/united buffers live on in other arrow objects)."""
        n_buffers, len_meta = struct.unpack_from("<IQ", view, 0)
        off = 12
        meta = bytes(view[off:off + len_meta])
        off += len_meta
        buffers = []
        for _ in range(n_buffers):
            off = _aligned(off + 8) - 8
            (blen,) = struct.unpack_from("<Q", view, off)
            off += 8
            buffers.append(view[off:off + blen])
            off += blen
        value, refs = self.deserialize(meta, buffers)
        return value, refs, buffers


_OOB_BYTES_THRESHOLD = 4096


class _OOBBytes:
    """Ships a large bytes/bytearray payload out-of-band: the pickle stream
    carries only a reconstructor; the payload rides as a zero-copy
    PickleBuffer (one memcpy into shm at write, one back out at get —
    instead of an extra full copy through the pickle stream)."""

    __slots__ = ("ctor", "value")

    def __init__(self, ctor, value):
        self.ctor = ctor
        self.value = value

    def __reduce_ex__(self, protocol):
        return self.ctor, (pickle.PickleBuffer(self.value),)


def _pre_serialize(value):
    """Convert device-resident jax arrays to host numpy so the object store
    stays host-side (TPU HBM is not host-mappable; SURVEY.md §7 hard part 4).
    The array round-trips back to device via ``jax.device_put`` on use.
    Large raw bytes go out-of-band (see _OOBBytes)."""
    if type(value) is bytes and len(value) > _OOB_BYTES_THRESHOLD:
        return _OOBBytes(bytes, value)
    if type(value) is bytearray and len(value) > _OOB_BYTES_THRESHOLD:
        return _OOBBytes(bytearray, value)
    import sys
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(value, jax.Array):
        import numpy as np
        return np.asarray(value)
    return value


# ---- device-array serialization fast path ----------------------------
# A jax.Array nested anywhere inside a value (a streamed pipeline
# activation tuple, an actor-call argument tree) used to ride jax's own
# __reduce__ THROUGH the pickle stream: a full in-band copy of the
# payload, then a second copy out at load. The scoped dispatch-table
# entry below turns any device array into (dtype, shape, PickleBuffer):
# the host view goes out-of-band — one memcpy into shm at write — and
# reconstructs as a zero-copy ``np.frombuffer`` view at read. Scoped to
# the object-store pickler (NOT copyreg-global) so user pickling
# semantics elsewhere are untouched.

_jax_dispatch: Optional[dict] = None


def _device_array_dispatch() -> Optional[dict]:
    global _jax_dispatch
    if _jax_dispatch is not None:
        return _jax_dispatch or None
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return None  # keep probing until jax shows up in the process
    try:
        from jax._src.array import ArrayImpl as _concrete
    except Exception:  # pragma: no cover - layout drift across versions
        _concrete = type(jax.numpy.zeros((), jax.numpy.float32))
    _jax_dispatch = {_concrete: _reduce_device_array}
    return _jax_dispatch


def _reduce_device_array(a):
    import numpy as np
    host = np.asarray(a)
    if host.nbytes < _OOB_BYTES_THRESHOLD:
        return (np.array, (host,))
    if not host.flags["C_CONTIGUOUS"]:
        host = np.ascontiguousarray(host)
    # ship as raw bytes: extension dtypes (bfloat16, float8_*) refuse
    # the buffer protocol, a uint8 view never does
    return (_restore_ndarray,
            (pickle.PickleBuffer(host.view(np.uint8)),
             host.dtype.name, host.shape))


def _restore_ndarray(buf, dtype_name: str, shape):
    import numpy as np
    try:
        dtype = np.dtype(dtype_name)
    except TypeError:
        # extension dtypes (bfloat16, float8_*) register via ml_dtypes
        import ml_dtypes
        dtype = np.dtype(getattr(ml_dtypes, dtype_name))
    return np.frombuffer(buf, dtype=np.uint8).view(dtype).reshape(shape)


def to_host(value):
    """Eagerly move a top-level device array to host numpy (no-op for
    anything else). The streaming worker calls this at yield time so
    the device fetch happens outside the store/report critical path."""
    import sys
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(value, jax.Array):
        import numpy as np
        return np.asarray(value)
    return value


_default_ctx: Optional[SerializationContext] = None


def default_context() -> SerializationContext:
    global _default_ctx
    if _default_ctx is None:
        _default_ctx = SerializationContext()
    return _default_ctx
