"""Deterministic, seed-driven fault injection for the control plane.

The reference gates releases on fault injection — ``testing_rpc_failure``
in ``ray_config_def.h`` lets any RPC be dropped/delayed by config, and the
chaos test utils SIGKILL raylets and workers mid-run. This module is that
subsystem for this runtime: every process's transport choke point
(``Runtime._flush_box``, ``NodeManager._send``/``_send_direct``,
``Controller._send``) consults one seeded PRNG stream before a message
hits the wire, so a failing run replays from its seed.

Three layers:

- **Message faults** (:class:`ChaosInjector`): per-message-type drop /
  delay / duplicate plus peer severing, decided from
  ``random.Random(f"{seed}:{stream}")`` where ``stream`` names the
  process role (``driver``, ``controller``, ``node``, ``worker:<n>`` —
  workers get a stable spawn index via ``RAY_TPU_CHAOS_ID``). Each
  message consumes a fixed number of draws, so the decision sequence for
  a given (seed, stream, config) is reproducible.
- **Scheduled partitions** (``ChaosConfig.partitions``): a time-indexed
  sever matrix — ``{"start": s, "end": s, "a": role, "b": role}`` cuts
  BOTH directions of the matching link (controller<->node,
  controller<->peer, node<->node) for the window, measured from each
  process's injector creation, then heals. Unlike probabilistic drops a
  partition cuts *everything* on the link, protected types included —
  real partitions don't read message headers. Recovery comes from the
  reliable-delivery layer (``core/reliable.py``) retransmitting the
  critical set after the heal, plus the periodic/reconnect machinery.
- **Duplicate hardening** (:class:`SeqDeduper`): while injection is
  active every injectable payload is stamped with a per-process wire
  sequence number and receivers drop replays — the duplication fault
  continuously proves the at-least-once dedup path (the reliable layer
  runs its own always-on instance against retransmit duplicates).
- **Disk faults** (:class:`DiskFaultInjector`): seeded ``EIO`` /
  ``ENOSPC`` / truncated-read faults on the spill path
  (``native_store.py`` spill writes and restore reads), proving the
  store degrades gracefully — retry with backoff, fall back to re-pull
  from another holder, and only then surface a typed
  ``ObjectLostError``.
- **Process faults** (:class:`ChaosMonkey`): driver/test-side scheduler
  for SIGKILLing workers and node managers mid-task and for controller
  pause/restart, driven by the same seed.

Activation is environment-driven so it propagates to every spawned
process: ``RAY_TPU_CHAOS_SEED=<int>`` turns injection on;
``RAY_TPU_CHAOS_CONFIG=<json>`` tunes probabilities (fields of
:class:`ChaosConfig`). Production runs never touch this module's hot
path — the injector handle is ``None`` and every hook is a single
attribute check.

Determinism note: decision *streams* are bit-reproducible per process;
end-to-end message interleaving still depends on OS scheduling. The
contract chaos tests rely on is that a fixed (seed, config, workload)
exercises the same fault mix and the asserted invariants (no hangs,
typed errors, drained refcounts, no leaked processes) hold on every
replay.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_SEED = "RAY_TPU_CHAOS_SEED"
ENV_CONFIG = "RAY_TPU_CHAOS_CONFIG"
ENV_STREAM_ID = "RAY_TPU_CHAOS_ID"

#: message types whose loss the runtime cannot recover from — the
#: registration handshake and RPC replies have no retransmit, and
#: RECONNECT is itself the recovery signal. Never injected.
PROTECTED_TYPES = frozenset({"REG", "REGR", "BYE", "RPL", "ERR", "RCN"})

#: default targets for a scalar ``drop_prob``: message types with
#: drop-recovery machinery. PING/HEARTBEAT are periodic; everything
#: else is covered by the reliable-delivery layer's ack/retransmit
#: (core/reliable.py) — which is what finally let the scalar mix cover
#: the whole critical one-way control plane (TASK_DISPATCH, ACTOR_CALL,
#: TASK_ASSIGN, TASK_DONE) instead of a hand-picked safe subset.
#: Request/reply types (SUB, KVO, ...) still need an explicit per-type
#: entry: their drop surfaces as the caller's RpcTimeoutError, which is
#: a worse failure mode to inject by default.
DEFAULT_DROPPABLE = frozenset({"RES", "PUT", "PNG", "HBT",
                               "DSP", "ACL", "ASG", "DON"})


@dataclass
class ChaosConfig:
    """Fault mix for one chaos run. ``drop``/``dup``/``delay`` map a
    message-type name (``"RES"``, ``"PUT"``, ... or ``"*"``) to a
    probability and override the scalar ``*_prob`` defaults.

    ``partitions`` is the scheduled sever matrix: a list of
    ``{"start": s, "end": s, "a": side, "b": side}`` windows (seconds
    from injector creation) where a side is one of ``"controller"``,
    ``"node"``, ``"driver"``, ``"worker"`` or ``"*"``. A window cuts
    every message, both directions, on links whose (sender role, target
    class) match — see :meth:`ChaosInjector._partitioned`. Driver and
    worker targets are indistinguishable at the sender (both are opaque
    28-byte DEALER identities), so either name matches any non-node
    peer; node identities are recognized by their ``b"N"`` prefix.

    ``disk``/``disk_fault_prob`` drive the spill-path disk faults
    (ops: ``"spill_write"`` -> EIO/ENOSPC, ``"restore_read"`` ->
    EIO/truncated read), consumed by :class:`DiskFaultInjector`."""

    seed: int = 0
    drop_prob: float = 0.0            # over DEFAULT_DROPPABLE
    dup_prob: float = 0.0             # over all unprotected types
    delay_prob: float = 0.0           # over all unprotected types
    delay_range_s: Tuple[float, float] = (0.002, 0.1)
    drop: Dict[str, float] = field(default_factory=dict)
    dup: Dict[str, float] = field(default_factory=dict)
    delay: Dict[str, float] = field(default_factory=dict)
    partitions: List[Dict] = field(default_factory=list)
    disk_fault_prob: float = 0.0      # over all spill-path disk ops
    disk: Dict[str, float] = field(default_factory=dict)

    @classmethod
    def from_env(cls) -> Optional["ChaosConfig"]:
        seed_raw = os.environ.get(ENV_SEED)
        cfg_raw = os.environ.get(ENV_CONFIG)
        if not seed_raw and not cfg_raw:
            return None
        cfg = cls()
        if cfg_raw:
            try:
                data = json.loads(cfg_raw)
            except ValueError:
                logger.warning("chaos: unparseable %s; injection disabled",
                               ENV_CONFIG)
                return None
            for k, v in data.items():
                if k == "delay_range_s":
                    cfg.delay_range_s = (float(v[0]), float(v[1]))
                elif hasattr(cfg, k):
                    setattr(cfg, k, v)
        if seed_raw:
            try:
                cfg.seed = int(seed_raw)
            except ValueError:
                logger.warning("chaos: non-integer %s=%r; injection "
                               "disabled", ENV_SEED, seed_raw)
                return None
        return cfg

    def env(self) -> Dict[str, str]:
        """Env vars that reproduce this config in a child process."""
        return {
            ENV_SEED: str(self.seed),
            ENV_CONFIG: json.dumps({
                "drop_prob": self.drop_prob, "dup_prob": self.dup_prob,
                "delay_prob": self.delay_prob,
                "delay_range_s": list(self.delay_range_s),
                "drop": self.drop, "dup": self.dup, "delay": self.delay,
                "partitions": self.partitions,
                "disk_fault_prob": self.disk_fault_prob,
                "disk": self.disk,
            }),
        }

    def _prob(self, table: Dict[str, float], scalar: float,
              scalar_set: Optional[frozenset], name: str) -> float:
        if name in PROTECTED_TYPES:
            return 0.0
        if name in table:
            return table[name]
        if "*" in table:
            return table["*"]
        if scalar_set is None or name in scalar_set:
            return scalar
        return 0.0

    def drop_p(self, name: str) -> float:
        return self._prob(self.drop, self.drop_prob, DEFAULT_DROPPABLE, name)

    def dup_p(self, name: str) -> float:
        return self._prob(self.dup, self.dup_prob, None, name)

    def delay_p(self, name: str) -> float:
        return self._prob(self.delay, self.delay_prob, None, name)

    def disk_p(self, op: str) -> float:
        return self.disk.get(op, self.disk.get("*", self.disk_fault_prob))


class SeqDeduper:
    """Receiver-side at-least-once filter: drops payloads whose
    ``(sender tag, wire seq)`` was already seen. Bounded LRU — chaos
    duplicates arrive within a handful of messages of the original, so a
    few thousand entries of history is orders of magnitude more than the
    replay window."""

    def __init__(self, cap: int = 8192):
        self._cap = cap
        self._seen: "collections.OrderedDict[tuple, None]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.dropped = 0

    def seen(self, key) -> bool:
        try:
            hash(key)
        except TypeError:
            return False
        with self._lock:
            if key in self._seen:
                self.dropped += 1
                return True
            self._seen[key] = None
            while len(self._seen) > self._cap:
                self._seen.popitem(last=False)
            return False


class ChaosInjector:
    """Per-process message-fault decider. ``plan_send`` is the single
    entry point the transports call; it returns the (possibly empty)
    list of ``(delay_s, payload)`` copies to actually ship."""

    def __init__(self, config: ChaosConfig, stream: str):
        self.config = config
        self.stream = stream
        self.role = stream.split(":", 1)[0]
        self._rng = random.Random(f"{config.seed}:{stream}")
        self._lock = threading.Lock()
        #: scheduled-partition clock origin: windows are seconds from
        #: injector creation (process start for spawned processes)
        self._t0 = time.monotonic()
        #: peers cut off (drop everything both directions this process
        #: sees). ``None`` severs the controller link.
        self._severed: set = set()
        #: receiver dedup key: unique per process *instance* (not per
        #: replay — it only needs to distinguish senders at a receiver)
        self._tag = os.urandom(8)
        self._seq = itertools.count(1)
        self.stats: "collections.Counter" = collections.Counter()

    def rng_for(self, name: str) -> random.Random:
        """Independent deterministic stream for an auxiliary consumer
        (e.g. the lease backoff), so its draws don't perturb the message
        decision sequence."""
        return random.Random(f"{self.config.seed}:{self.stream}:{name}")

    # ------------------------------------------------------------- sever
    def sever(self, peer: Optional[bytes]) -> None:
        with self._lock:
            self._severed.add(peer)

    def heal(self, peer: Optional[bytes] = None) -> None:
        with self._lock:
            if peer is None:
                self._severed.clear()
            else:
                self._severed.discard(peer)

    # -------------------------------------------------- partitions
    @staticmethod
    def _side_matches_role(side: str, role: str) -> bool:
        return side == "*" or side == role or \
            (side in ("driver", "worker", "peer")
             and role in ("driver", "worker"))

    @staticmethod
    def _target_class(target: Optional[bytes]) -> str:
        if target is None:
            return "controller"
        if len(target) == 28 and target[:1] == b"N":
            return "node"
        return "peer"  # worker or driver: indistinguishable identities

    @classmethod
    def _side_matches_target(cls, side: str, tclass: str) -> bool:
        return side == "*" or side == tclass or \
            (side in ("driver", "worker", "peer") and tclass == "peer")

    def _partitioned(self, target: Optional[bytes], now: float) -> bool:
        """True when a scheduled partition window currently severs the
        (this role -> target) link. Pure time check — consumes no RNG
        draws, so adding partitions to a config shifts no other fault
        decisions."""
        t = now - self._t0
        tclass = self._target_class(target)
        for p in self.config.partitions:
            if not (p.get("start", 0.0) <= t < p.get("end", float("inf"))):
                continue
            a, b = p.get("a", "*"), p.get("b", "*")
            if (self._side_matches_role(a, self.role)
                    and self._side_matches_target(b, tclass)) or \
               (self._side_matches_role(b, self.role)
                    and self._side_matches_target(a, tclass)):
                return True
        return False

    # -------------------------------------------------------------- plan
    def plan_send(self, target: Optional[bytes], mtype: bytes,
                  payload: Any) -> List[Tuple[float, Any]]:
        """Decide the fate of one outgoing message. ``target`` is the
        peer identity (``None`` = the controller link). Returns
        ``[(delay_s, payload), ...]``: empty list = dropped, two entries
        = duplicated. Injectable dict payloads are stamped with a wire
        sequence number for receiver-side dedup."""
        name = mtype.decode("ascii", "replace")
        # scheduled partitions cut EVERYTHING on the link, protected
        # types included — a real partition doesn't read headers
        if self.config.partitions and \
                self._partitioned(target, time.monotonic()):
            self.stats[("partition", name)] += 1
            return []
        if name in PROTECTED_TYPES:
            return [(0.0, payload)]
        cfg = self.config
        with self._lock:
            if self._severed and (target in self._severed):
                self.stats[("sever", name)] += 1
                return []
            # fixed draw count per message keeps the stream replayable
            r_drop = self._rng.random()
            r_dup = self._rng.random()
            r_delay = self._rng.random()
            r_amount = self._rng.random()
            n = next(self._seq)
        if r_drop < cfg.drop_p(name):
            self.stats[("drop", name)] += 1
            return []
        if isinstance(payload, dict):
            payload = dict(payload, __wseq__=(self._tag, n))
        lo, hi = cfg.delay_range_s
        delay = lo + r_amount * (hi - lo) \
            if r_delay < cfg.delay_p(name) else 0.0
        if delay > 0.0:
            self.stats[("delay", name)] += 1
        out = [(delay, payload)]
        if isinstance(payload, dict) and r_dup < cfg.dup_p(name):
            # the copy carries the SAME wire seq: receivers must drop it
            self.stats[("dup", name)] += 1
            out.append((0.0, payload))
        return out


def maybe_injector(role: str) -> Optional[ChaosInjector]:
    """The per-process activation hook: returns an injector when chaos
    env vars are set, else ``None`` (the common case — callers keep a
    ``None`` handle and skip every chaos branch)."""
    cfg = ChaosConfig.from_env()
    if cfg is None:
        return None
    sid = os.environ.get(ENV_STREAM_ID, "")
    stream = f"{role}:{sid}" if sid else role
    inj = ChaosInjector(cfg, stream)
    logger.warning("chaos: fault injection ACTIVE (seed=%d stream=%s)",
                   cfg.seed, stream)
    return inj


def check_dedup(dedup: Optional[SeqDeduper], payload: Any) -> bool:
    """Receiver-side hook: pops the wire seq stamp and returns True when
    the payload is a duplicate that must be discarded."""
    if dedup is None or not isinstance(payload, dict):
        return False
    key = payload.pop("__wseq__", None)
    return key is not None and dedup.seen(key)


class DiskFaultInjector:
    """Seeded fault decider for the spill path's disk I/O
    (``native_store.py``). One deterministic stream per process,
    independent of the message-fault draws (``:disk`` suffix), so
    enabling disk faults shifts no message decisions.

    Ops and fault kinds:

    - ``spill_write``: ``"eio"`` | ``"enospc"`` — the spill write is
      refused; the store keeps the object resident (it is still the
      only copy) and retries on a later sweep.
    - ``restore_read``: ``"eio"`` (transient — the store reports
      ``"retry"`` until a strike cap, then declares the local backing
      copy lost) | ``"truncate"`` (a torn file: immediately lost).
    """

    def __init__(self, config: ChaosConfig, stream: str):
        self.config = config
        self.stream = stream
        self._rng = random.Random(f"{config.seed}:{stream}:disk")
        self._lock = threading.Lock()
        self.stats: "collections.Counter" = collections.Counter()

    def fault(self, op: str) -> Optional[str]:
        """Draw the fate of one disk operation: None (healthy) or a
        fault kind. Fixed two draws per call keeps the stream
        replayable."""
        p = self.config.disk_p(op)
        with self._lock:
            r = self._rng.random()
            r_kind = self._rng.random()
        if p <= 0.0 or r >= p:
            return None
        if op == "spill_write":
            kind = "enospc" if r_kind < 0.33 else "eio"
        else:
            kind = "truncate" if r_kind < 0.25 else "eio"
        self.stats[(op, kind)] += 1
        return kind


def maybe_disk_injector(role: str) -> Optional[DiskFaultInjector]:
    """Spill-path activation hook (mirrors :func:`maybe_injector`):
    returns a disk-fault injector when chaos env vars are set with a
    non-zero disk fault mix, else None."""
    cfg = ChaosConfig.from_env()
    if cfg is None or (cfg.disk_fault_prob <= 0.0 and not cfg.disk):
        return None
    sid = os.environ.get(ENV_STREAM_ID, "")
    stream = f"{role}:{sid}" if sid else role
    inj = DiskFaultInjector(cfg, stream)
    logger.warning("chaos: disk-fault injection ACTIVE (seed=%d "
                   "stream=%s)", cfg.seed, stream)
    return inj


class ChaosMonkey:
    """Process-level fault scheduler for tests: SIGKILLs workers and
    node managers mid-task and pauses/restarts the controller, all
    ordered by one seeded PRNG (reference: the chaos/node-killer test
    utils). Operates on the in-process head (``ray_tpu.api._head``) of
    the calling driver."""

    def __init__(self, seed: int, head=None):
        self.rng = random.Random(f"{seed}:monkey")
        self._head = head
        self.log: List[tuple] = []

    def _get_head(self):
        if self._head is not None:
            return self._head
        import ray_tpu.api as api
        return api._head

    # ------------------------------------------------------------ workers
    def worker_pids(self) -> Dict[bytes, int]:
        node = self._get_head().node
        with node._workers_lock:
            return {ident: proc.pid
                    for ident, proc in node.workers.items()}

    def kill_random_worker(self, exclude: Tuple[int, ...] = ()
                           ) -> Optional[int]:
        """SIGKILL one currently-registered worker of the head node,
        chosen deterministically; returns its pid (None if no
        candidates)."""
        pids = self.worker_pids()
        candidates = sorted(p for p in pids.values() if p not in exclude)
        if not candidates:
            return None
        victim = self.rng.choice(candidates)
        self.log.append(("kill_worker", victim))
        try:
            os.kill(victim, signal.SIGKILL)
        except ProcessLookupError:
            pass
        return victim

    def kill_node_proc(self, proc) -> None:
        """SIGKILL a standalone node-manager process (a
        ``cluster_utils`` node's subprocess)."""
        self.log.append(("kill_node", proc.pid))
        try:
            proc.kill()
        except Exception:
            pass

    # --------------------------------------------------------- controller
    def restart_controller(self):
        """kill -9 equivalent for the in-process controller: abandon it
        without any state flush (durability must come from the WAL
        alone) and start a fresh one on the same session."""
        from ray_tpu.core.controller import Controller
        head = self._get_head()
        old = head.controller
        self.log.append(("restart_controller",))
        old._shutdown.set()
        rel = getattr(old, "_reliable", None)
        if rel is not None:
            # a kill -9 takes the retransmit thread with it too
            rel.stop()
        try:
            old._wake_send.send(b"")
        except Exception:
            pass
        if old._thread is not None:
            old._thread.join(timeout=10)
        head.controller = Controller(head.session_dir, old.config)
        head.controller.start()
        return head.controller

    def pause_controller(self, seconds: float) -> threading.Thread:
        """Wedge the controller event loop for ``seconds`` (GC-pause /
        overload simulation). Returns the thread holding the loop."""
        head = self._get_head()
        self.log.append(("pause_controller", seconds))

        def hold():
            try:
                head.controller.call_on_loop(
                    lambda: time.sleep(seconds), timeout=seconds + 30.0)
            except Exception:
                pass

        t = threading.Thread(target=hold, name="chaos-pause", daemon=True)
        t.start()
        return t
